"""Per-op HLO cost ledger: the program-anatomy half of the observatory.

`metrics/xla_obs.py` records each compiled program's `cost_analysis()`
TOTALS — one flops number, one bytes number per program. That is enough
to rank programs against each other but useless for the question ROADMAP
item 1 (the fused paged-attention kernel) has to answer: of the paged
decode program's cost, how much is the full-lane page GATHER, how much
the int8 dequant CONVERTs, how much the written-page SCATTER, and how
much the attention/MLP dots the kernel must keep? This module parses the
compiled program's HLO text (`compiled.as_text()`, the same line-scan
discipline as `metrics.mesh_obs.parse_hlo_collectives`) into a per-op-
CATEGORY ledger:

    gather / scatter / dot / convert / fusion / dynamic-slice /
    custom-call / parameter / other

with three numbers per category — op count, estimated flops, and
output-shape bytes — plus the top-k heaviest NAMED ops (with their
jax-level `metadata op_name` source when the compiler kept it), so an
"opaque 27% tax" becomes "%gather.12, 5.2 MB output, from
jit(decode)/gather_lanes/gather".

Conventions (shared with the collective ledger, documented here once):

* Counts are STATIC — an op inside a `while` body (the decode scan)
  counts once, not per trip. The ledger answers "which ops, how big",
  not cycle-exact totals.
* Bytes are the op's OUTPUT shape bytes (tuple outputs summed) — a
  uniform traffic proxy across op kinds. `parameter` ops in the ENTRY
  computation are counted (their "output" is the argument the program
  reads), so the all-category bytes total approximates cost_analysis's
  operand+output "bytes accessed"; parameters of fused/sub-computations
  alias an already-counted operand and are skipped.
* Flops follow XLA's own cost-analysis conventions closely enough to
  reconcile on simple programs (pinned in tests/test_hlo_cost.py):
  elementwise/transcendental ops count one flop per output element,
  `dot` counts ``2 * output_elems * contraction_size`` (contraction
  parsed from the operand shape + `lhs_contracting_dims`), `reduce`
  counts its input elements, and pure data movement (gather, scatter,
  slice, broadcast, copy, bitcast, parameter, ...) counts zero. A
  `fusion` op's flops live on the INNER ops of its fused computation
  (which the scan also walks); the fusion line itself contributes only
  its output bytes — the buffer the fusion materializes.

Nothing here imports jax: the input is a string, so the parser is unit-
testable on crafted HLO and usable offline on `obs_hlo_dir` dumps.
"""

from __future__ import annotations

import re

# category order is the display order everywhere (statusz, trace
# summary, README table) — the paged-tax story first, remainder last
CATEGORIES = (
    "gather",
    "scatter",
    "dot",
    "convert",
    "fusion",
    "dynamic-slice",
    "custom-call",
    "parameter",
    "other",
)

_CATEGORY_OF = {
    "gather": "gather",
    "scatter": "scatter",
    "select-and-scatter": "scatter",
    "dot": "dot",
    "convolution": "dot",
    "convert": "convert",
    "fusion": "fusion",
    "dynamic-slice": "dynamic-slice",
    "dynamic-update-slice": "dynamic-slice",
    "custom-call": "custom-call",
    "parameter": "parameter",
}

# data movement / bookkeeping: zero flops (the XLA cost-analysis
# convention the reconciliation test pins). Everything not listed and
# not special-cased (dot, reduce) counts one flop per output element.
_ZERO_FLOP_OPS = frozenset({
    "parameter", "constant", "broadcast", "bitcast", "bitcast-convert",
    "reshape", "transpose", "copy", "copy-start", "copy-done", "tuple",
    "get-tuple-element", "gather", "scatter", "dynamic-slice",
    "dynamic-update-slice", "slice", "concatenate", "pad", "iota",
    "reverse", "after-all", "all-reduce", "all-gather", "reduce-scatter",
    "all-to-all", "collective-permute", "fusion", "custom-call", "call",
    "while", "conditional", "optimization-barrier", "domain", "send",
    "recv", "send-done", "recv-done", "infeed", "outfeed",
    "partition-id", "replica-id", "rng-bit-generator", "get-dimension-size",
})

# "%name = <output shape(s)> <op>(" — defining occurrences only, the
# parse_hlo_collectives discipline: operand references live inside the
# parens of another op's definition and never follow " = ".
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[^\s=]+)\s*=\s*"
    r"(?P<out>\([^)]*\)|\S+)\s+"
    r"(?P<op>[a-z][a-z0-9\-]*)\("
)

_SHAPE_RE = re.compile(
    r"(?P<dt>[a-z]\d*[a-z0-9]*|pred)\[(?P<dims>[\d,]*)\]"
)

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{(?P<dims>[\d,]*)\}")
_OP_NAME_RE = re.compile(r'op_name="(?P<src>[^"]*)"')


def _atom_elems_bytes(dt: str, dims: str) -> tuple[int, int]:
    nbytes = _DTYPE_BYTES.get(dt)
    if nbytes is None:
        digits = re.search(r"(\d+)$", dt)
        nbytes = max(int(digits.group(1)) // 8, 1) if digits else 4
    n = 1
    if dims:
        for d in dims.split(","):
            n *= int(d)
    return n, n * nbytes


def _shape_elems_bytes(text: str) -> tuple[int, int]:
    """(total elements, total bytes) of every shape atom in `text` —
    a single shape, or a tuple shape summed."""
    elems = 0
    nbytes = 0
    for m in _SHAPE_RE.finditer(text):
        e, b = _atom_elems_bytes(m.group("dt"), m.group("dims"))
        elems += e
        nbytes += b
    return elems, nbytes


def classify_op(op: str) -> str:
    """HLO opcode -> ledger category (CATEGORIES)."""
    return _CATEGORY_OF.get(op, "other")


def _dot_flops(line: str, tail: str, out_elems: int) -> int:
    """``2 * output_elems * contraction_size`` with the contraction
    parsed from the first operand's shape atom + lhs_contracting_dims;
    falls back to ``2 * output_elems`` when either is absent (elided
    operand shapes in minimized dumps)."""
    lhs = _SHAPE_RE.search(tail)
    contract = _CONTRACT_RE.search(line)
    if lhs is None or contract is None:
        return 2 * out_elems
    dims_txt = lhs.group("dims")
    lhs_dims = [int(d) for d in dims_txt.split(",")] if dims_txt else []
    k = 1
    for i in contract.group("dims").split(","):
        if i == "":
            continue
        idx = int(i)
        if idx < len(lhs_dims):
            k *= lhs_dims[idx]
    return 2 * out_elems * k


def parse_hlo_costs(hlo_text: str, top_k: int = 5) -> dict:
    """Scan an HLO module's text into the per-op-category cost ledger.

    Returns::

        {"ops": N, "flops": F, "bytes": B,
         "categories": {category: {"ops": n, "flops": f, "bytes": b}},
         "top_ops": [{"name", "op", "category", "flops", "bytes"
                      [, "source"]}, ...]}   # heaviest first

    ``top_ops`` ranks by ``max(flops, bytes)`` — a zero-flop gather
    moving megabytes is exactly as interesting as a dot burning them —
    and carries the jax-level ``metadata op_name`` as ``source`` when
    present. Categories with no ops are ABSENT, never zero-filled; an
    empty module returns zero totals and an empty category dict.
    """
    categories: dict[str, dict[str, int]] = {}
    ops_list: list[dict] = []
    total_ops = 0
    total_flops = 0
    total_bytes = 0
    in_entry = False
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and " = " not in stripped:
            # a computation header ("%fused_computation (...) -> ... {",
            # "ENTRY %main (...) {", while/reduce region bodies): only
            # the entry computation's parameters are argument traffic
            in_entry = stripped.startswith("ENTRY")
            continue
        m = _DEF_RE.match(line)
        if m is None:
            continue
        op = m.group("op")
        if op == "parameter" and not in_entry:
            # a sub-computation's parameter aliases an operand the
            # caller already counted — skipping it keeps the bytes
            # total an operand+output traffic proxy, not double counts
            continue
        out = m.group("out")
        out_elems, out_bytes = _shape_elems_bytes(out)
        tail = line[m.end():]
        if op in ("dot", "convolution"):
            flops = _dot_flops(line, tail, out_elems)
        elif op in ("reduce", "reduce-window"):
            first = _SHAPE_RE.search(tail)
            flops = (
                _atom_elems_bytes(first.group("dt"), first.group("dims"))[0]
                if first is not None else out_elems
            )
        elif op in _ZERO_FLOP_OPS:
            flops = 0
        else:
            flops = out_elems
        cat = classify_op(op)
        d = categories.setdefault(cat, {"ops": 0, "flops": 0, "bytes": 0})
        d["ops"] += 1
        d["flops"] += flops
        d["bytes"] += out_bytes
        total_ops += 1
        total_flops += flops
        total_bytes += out_bytes
        entry = {
            "name": m.group("name"),
            "op": op,
            "category": cat,
            "flops": flops,
            "bytes": out_bytes,
        }
        src = _OP_NAME_RE.search(line)
        if src is not None:
            entry["source"] = src.group("src")
        ops_list.append(entry)
    ops_list.sort(key=lambda e: -max(e["flops"], e["bytes"]))
    return {
        "ops": total_ops,
        "flops": total_flops,
        "bytes": total_bytes,
        "categories": categories,
        "top_ops": ops_list[:top_k],
    }


def best_anatomy(candidates) -> dict | None:
    """Pick the representative ledger from an iterable of per-signature
    candidates: the heaviest-output-bytes NON-EMPTY parse (the
    steady-state variant — the collective-ledger convention), or None
    when nothing parsed. ONE implementation shared by the live registry
    (statusz + anatomy_stats) and the offline trace join, so the three
    surfaces can never pick differently."""
    best = None
    for a in candidates:
        if not a or not a.get("ops"):
            continue
        if best is None or a.get("bytes", 0) > best.get("bytes", 0):
            best = a
    return best


def format_anatomy(anatomy: dict) -> str:
    """Human-readable per-program anatomy report (the `anatomy` section
    of `summarize_trace` / the statusz `programs.<name>.anatomy` dicts:
    {program: parse_hlo_costs result}), or "" when empty."""
    if not anatomy:
        return ""
    lines = ["program anatomy (per-op HLO ledger: static counts, "
             "output-shape bytes):"]
    for prog, d in sorted(anatomy.items(),
                          key=lambda kv: -kv[1].get("bytes", 0)):
        lines.append(
            f"  {prog}: {d.get('ops', 0)} ops, "
            f"{d.get('flops', 0):.3g} flops, {d.get('bytes', 0)} bytes"
        )
        cats = d.get("categories") or {}
        for cat in CATEGORIES:
            c = cats.get(cat)
            if not c:
                continue
            lines.append(
                f"    {cat:<14} x{c['ops']:<4} flops {c['flops']:>12.3g} "
                f"bytes {c['bytes']:>12}"
            )
        top = d.get("top_ops") or []
        if top:
            lines.append("    heaviest ops:")
            for t in top:
                src = t.get("source")
                lines.append(
                    f"      {t['name']:<24} {t['category']:<14} "
                    f"flops {t['flops']:>12.3g} bytes {t['bytes']:>12}"
                    + (f"  [{src}]" if src else "")
                )
    return "\n".join(lines)
