"""Qualitative-eval artifacts.

Capability targets: the reconstruction comparison grids of
autoencoder/autoencoder.ipynb cell 9 and variational autoencoder.ipynb
cell 9 (originals vs reconstructions, saved as PNG here instead of shown
inline), and deepseekv3's generated-text snapshots (cell 51 writes
`generated_{step}.txt` at each eval).
"""

from __future__ import annotations

import os

import numpy as np


def save_reconstruction_grid(
    originals: np.ndarray,
    reconstructions: np.ndarray,
    path: str,
    *,
    n: int = 8,
    side: int | None = None,
) -> str:
    """Two-row PNG: originals on top, reconstructions below.

    Accepts flattened (B, H*W) or image (B, H, W[, C]) arrays in [0, 1].
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    def to_img(x):
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            s = side or int(round(x.size**0.5))
            x = x.reshape(s, -1)
        if x.ndim == 3 and x.shape[-1] == 1:
            x = x[..., 0]
        return x

    n = min(n, len(originals), len(reconstructions))
    fig, axes = plt.subplots(2, n, figsize=(1.2 * n, 2.6))
    if n == 1:
        axes = axes.reshape(2, 1)
    for i in range(n):
        for row, batch in enumerate((originals, reconstructions)):
            ax = axes[row][i]
            ax.imshow(to_img(batch[i]), cmap="gray", vmin=0.0, vmax=1.0)
            ax.axis("off")
    axes[0][0].set_title("original", fontsize=8, loc="left")
    axes[1][0].set_title("reconstruction", fontsize=8, loc="left")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fig.savefig(path, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return path


def save_activation_curves(path: str) -> str:
    """Reference curves for the activation-function family — the plotting
    capability of activation functions/ReLU.ipynb cells 7-10 and GELU.ipynb,
    drawn from the shared ops (one implementation, not per-notebook)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import jax.numpy as jnp

    from solvingpapers_tpu import ops

    x = jnp.linspace(-4, 4, 401)
    curves = [
        ("relu", ops.relu(x)),
        ("leaky_relu", ops.leaky_relu(x)),
        ("prelu(0.25)", ops.prelu(x, 0.25)),
        ("elu", ops.elu(x)),
        ("gelu_tanh", ops.gelu_tanh(x)),
        ("silu/swish", ops.silu(x)),
    ]
    fig, axes = plt.subplots(2, 3, figsize=(10, 5.5), sharex=True)
    for ax, (name, y) in zip(axes.flat, curves):
        ax.plot(np.asarray(x), np.asarray(y))
        ax.axhline(0, lw=0.5, color="gray")
        ax.axvline(0, lw=0.5, color="gray")
        ax.set_title(name, fontsize=9)
    fig.tight_layout()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def save_text_sample(text: str, directory: str, step: int) -> str:
    """deepseekv3 cell 51's `generated_{step}.txt` artifact."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"generated_{step}.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return path
