"""Mergeable log-bucketed latency histograms (the HdrHistogram shape).

`metrics.writer.Ring` kept the last N observations and summarized with
`np.percentile` — fine for one process's recent window, but (1) a
bounded ring is a BIASED estimator under load (the window holds whatever
arrived last, so a burst evicts the tail that p99 lives in), and (2) two
rings cannot be combined: there is no way to aggregate latency across
the replicas ROADMAP item 2 introduces without shipping raw samples.

`LogHistogram` fixes both with the HdrHistogram/Prometheus shape:

* FIXED log-spaced bucket boundaries, chosen at construction
  (``lo * 10**(i / buckets_per_decade)``), so every instance with the
  same layout has the same edges — the property that makes merge exact;
* O(1) record (one log10 + one integer increment), no per-observation
  allocation, total count and sum tracked alongside (plus exact min/max,
  which cost nothing and let quantiles clamp to observed values);
* EXACT merge: same-layout histograms combine by adding count arrays —
  ``merge(shard_a, shard_b)`` is indistinguishable from one histogram
  that saw every observation (bucket counts identical by construction;
  the float `sum` differs only by addition order, < 1 ulp per merge);
* bounded-error quantiles: the estimate lands in the same bucket as the
  exact nearest-rank sample, so the error is at most that bucket's
  width — relative error ``10**(1/buckets_per_decade) - 1`` (~15% at
  the default 16 buckets/decade), pinned by a property test;
* native Prometheus exposition: `bucket_bounds`/`cumulative_counts`
  feed `PrometheusTextWriter`'s ``_bucket{le=...}/_sum/_count``
  rendering, so PromQL's `histogram_quantile` + `sum by (le)` work
  across replicas — the pull-side version of the merge property.

The default layout [100 µs, 10 000 s) at 16 buckets per decade covers
TTFT/ITL/e2e on everything from a TPU pod to the CPU bench; values
outside it land in the underflow/overflow buckets (counted, clamped to
the observed min/max in quantiles, never dropped).
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["LogHistogram"]


class LogHistogram:
    """Fixed-layout log-bucketed histogram of non-negative observations.

    API mirrors `metrics.writer.Ring` where they overlap (`add`, `mean`,
    `percentiles`, `__len__`) so it can replace the ring as a latency
    backend without touching the summary plumbing.
    """

    __slots__ = ("lo", "hi", "buckets_per_decade", "n_buckets", "counts",
                 "count", "sum", "min", "max", "_log_lo", "_scale")

    def __init__(self, lo: float = 1e-4, hi: float = 1e4,
                 buckets_per_decade: int = 16):
        if not (lo > 0 and hi > lo):
            raise ValueError(
                f"need 0 < lo < hi, got lo={lo} hi={hi}"
            )
        if buckets_per_decade < 1:
            raise ValueError(
                f"buckets_per_decade must be >= 1, got {buckets_per_decade}"
            )
        self.lo = float(lo)
        self.hi = float(hi)
        self.buckets_per_decade = int(buckets_per_decade)
        self.n_buckets = int(
            math.ceil(round(math.log10(hi / lo) * buckets_per_decade, 9))
        )
        # [underflow] + n log buckets + [overflow]
        self.counts = np.zeros(self.n_buckets + 2, np.int64)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._log_lo = math.log10(self.lo)
        self._scale = float(buckets_per_decade)

    # ------------------------------------------------------------ layout

    @property
    def layout(self) -> tuple:
        """Merge-compatibility key: histograms merge iff layouts match."""
        return (self.lo, self.hi, self.buckets_per_decade)

    def edge(self, i: int) -> float:
        """Upper edge of log bucket i in [0, n_buckets)."""
        return self.lo * 10.0 ** ((i + 1) / self._scale)

    def bucket_bounds(self) -> list[float]:
        """Every bucket's inclusive upper bound, Prometheus `le` order:
        underflow (le=lo), the log buckets, overflow (le=+inf)."""
        return ([self.lo]
                + [self.edge(i) for i in range(self.n_buckets)]
                + [math.inf])

    def _index(self, v: float) -> int:
        if v < self.lo:
            return 0
        if v >= self.hi:
            return self.n_buckets + 1
        i = int(math.floor((math.log10(v) - self._log_lo) * self._scale))
        # float rounding at an exact edge may land one off; clamp into
        # the log-bucket range (the under/overflow cases returned above)
        return 1 + min(max(i, 0), self.n_buckets - 1)

    # ------------------------------------------------------------ record

    def add(self, value: float, n: int = 1) -> None:
        """Record `value` `n` times (n > 1 is the decode block's
        amortized per-token gap — one bucket increment either way)."""
        v = max(float(value), 0.0)
        self.counts[self._index(v)] += n
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    # ------------------------------------------------------------- merge

    def merge_from(self, other: "LogHistogram") -> "LogHistogram":
        """Fold `other`'s observations into self (exact: bucket counts
        add; layouts must match).

        Safe against a LIVE `other` (the fleet /metrics path merges
        replicas that are still recording): the shard's buckets are
        copied ONCE and the merged count derived FROM that copy, so an
        observation landing mid-merge is wholly present or wholly
        absent from the bucket/count pair — never torn across them
        (`add` updates counts before count, so reading count instead
        could disagree with the buckets in either direction). `sum`
        is a single read and may miss the same in-flight observation
        the buckets missed — the ordinary scrape-boundary skew. For
        quiescent shards this is byte-identical to the naive fold, so
        the exact-merge contract (merge-of-shards == shard-of-merged)
        is unchanged."""
        if other.layout != self.layout:
            raise ValueError(
                f"cannot merge histograms with different layouts: "
                f"{self.layout} vs {other.layout}"
            )
        shard = other.counts.copy()
        self.counts += shard
        self.count += int(shard.sum())
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    @classmethod
    def merge(cls, hists) -> "LogHistogram":
        """One histogram equivalent to having recorded every shard's
        observations (per-replica aggregation)."""
        hists = list(hists)
        if not hists:
            raise ValueError("merge needs at least one histogram")
        out = cls(*hists[0].layout[:2],
                  buckets_per_decade=hists[0].layout[2])
        for h in hists:
            out.merge_from(h)
        return out

    # ----------------------------------------------------------- summary

    def __len__(self) -> int:
        return self.count

    def mean(self) -> float:
        return self.sum / self.count if self.count else float("nan")

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate, clamped to the observed
        [min, max]. The estimate lands in the bucket holding the exact
        nearest-rank sample, so |estimate - exact| <= that bucket's
        width (and a single-bucket population — e.g. one observation —
        reports exactly)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return float("nan")
        rank = max(1, math.ceil(q * self.count))
        cum = 0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            cum += int(c)
            if cum >= rank:
                idx = i
                break
        if idx == 0:
            rep = self.min  # underflow: [0, lo) — min/max are the only
        elif idx == self.n_buckets + 1:
            rep = self.max  # overflow: [hi, inf) — exact facts held
            # about values outside the layout
        else:
            lo_edge = self.lo * 10.0 ** ((idx - 1) / self._scale)
            rep = math.sqrt(lo_edge * self.edge(idx - 1))  # geometric mid
        return min(max(rep, self.min), self.max)

    def percentiles(self, qs: tuple[float, ...] = (50, 95, 99)) -> dict:
        """`{"p50": ..., ...}` — the Ring's summary shape (percent
        inputs, fractional labels kept)."""
        if self.count == 0:
            return {}
        out = {}
        for q in qs:
            label = f"p{int(q)}" if float(q).is_integer() else f"p{q}"
            out[label] = self.quantile(q / 100.0)
        return out

    # -------------------------------------------------------- exposition

    def cumulative_counts(self) -> list[int]:
        """Cumulative counts aligned with `bucket_bounds()` (Prometheus
        `_bucket` semantics: count of observations <= each `le`)."""
        return np.cumsum(self.counts).tolist()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"LogHistogram(n={self.count}, sum={self.sum:.6g}, "
                f"layout={self.layout})")
