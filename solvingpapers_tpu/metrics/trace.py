"""Flight recorder: bounded-ring structured event tracing for the serving
and training engines.

`ServeMetrics` answers aggregate questions ("what is p99 TTFT"); this
module answers the per-request and per-step ones ("why was THIS request's
TTFT 900 ms", "what did step 1412 spend its time on") — the debugging
substrate production serving stacks (vLLM request metrics, Orca
iteration-level analyses) build batching/cache post-mortems on. Three
pieces:

* `FlightRecorder` — a thread-safe bounded ring of typed events
  (monotonic timestamps, category, display track, optional request id,
  small payload dicts). Recording is append-one-tuple-under-a-lock;
  everything expensive (JSON, flow synthesis, track naming) happens at
  export. When tracing is off the engines hold `None` instead of a
  recorder, so every hook site is a single `is not None` branch.

* Chrome trace-event export (`FlightRecorder.export_chrome`) — JSON
  loadable in Perfetto / `chrome://tracing`: one named track per KV slot
  (plus engine / queue / prefix / train tracks) and one flow per request,
  so a request's submit -> queue -> admit -> splice -> prefill ->
  decode-blocks -> finish lifecycle reads as a connected timeline.

* `AnomalyMonitor` — watches finishes (timeout / cancelled), rejection
  bursts, and engine steps exceeding k x the rolling-median step time;
  on trigger it appends the last N ring events plus a metrics snapshot
  to a JSONL file for post-mortem, then keeps going (bounded by
  `max_dumps` so a pathological run cannot fill the disk).

`summarize_trace` / `format_summary` rebuild per-request timelines from
an exported trace (the `cli trace-summary` command): for every request
the lifecycle spans partition its wall time exactly — queue
(submit -> admit) + prefill (admit -> first token) + decode (first token
-> finish) — because the engine stamps them from the same
`Request.submit_time` / `admit_time` / `first_token_time` /
`finish_time` clock readings the latency metrics use.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Callable


@dataclasses.dataclass(frozen=True, slots=True)
class TraceEvent:
    """One recorded event. `ph` follows the Chrome trace-event phases the
    exporter emits: "X" complete (ts + dur), "i" instant, "C" counter.
    `track` is the display lane ("engine", "queue", "prefix", "train",
    "slot<N>"); `req` binds the event into a request's flow."""

    name: str
    cat: str
    track: str
    ph: str
    ts: float  # seconds on the recorder's clock (monotonic)
    dur: float = 0.0  # seconds; complete events only
    req: int | None = None
    args: dict | None = None

    def to_dict(self) -> dict:
        d = {"name": self.name, "cat": self.cat, "track": self.track,
             "ph": self.ph, "ts": self.ts, "dur": self.dur}
        if self.req is not None:
            d["req"] = self.req
        if self.args:
            d["args"] = self.args
        return d


# fixed display order for the well-known tracks; slot tracks sort by
# index after them, then per-device stage tracks (the mesh observatory's
# pipeline lanes), anything else alphabetically at the end
_TRACK_ORDER = {"engine": 0, "queue": 1, "prefix": 2, "http": 3,
                "train": 4, "mesh": 5, "router": 6}


def _track_sort_key(track: str) -> tuple:
    if track in _TRACK_ORDER:
        return (0, _TRACK_ORDER[track], 0, track)
    if track.startswith("slot") and track[4:].isdigit():
        return (1, 0, int(track[4:]), track)
    if track.startswith("stage") and track[5:].isdigit():
        return (1, 1, int(track[5:]), track)
    return (2, 0, 0, track)


class FlightRecorder:
    """Thread-safe bounded ring of `TraceEvent`s.

    `capacity` bounds memory: the ring keeps the newest events (a
    long-lived serving loop records unboundedly many; the recent window
    is what an anomaly dump or an export wants). `clock` defaults to
    `time.monotonic` and is injectable so the serving engine can share
    its patchable `serve.metrics.now` clock with the latency metrics —
    one time base for spans and TTFT makes the trace-summary phase sums
    exact against measured latencies.
    """

    def __init__(self, capacity: int = 65536,
                 clock: Callable[[], float] = time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.clock = clock
        self._buf: deque[TraceEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.total_recorded = 0

    def __len__(self) -> int:
        return len(self._buf)

    def _record(self, ev: TraceEvent) -> None:
        with self._lock:
            self._buf.append(ev)
            self.total_recorded += 1

    # ----------------------------------------------------------- recording

    def instant(self, name: str, cat: str, track: str, *,
                req: int | None = None, ts: float | None = None,
                **args) -> None:
        self._record(TraceEvent(
            name, cat, track, "i", self.clock() if ts is None else ts,
            req=req, args=args or None,
        ))

    def complete(self, name: str, cat: str, track: str, *, ts: float,
                 dur: float, req: int | None = None, **args) -> None:
        """A finished span: `ts` start, `dur` seconds (recorded at end —
        the ring holds only completed spans, so a reader never sees a
        dangling begin)."""
        self._record(TraceEvent(
            name, cat, track, "X", ts, dur=max(dur, 0.0), req=req,
            args=args or None,
        ))

    def counter(self, name: str, cat: str, track: str, *,
                ts: float | None = None, **values) -> None:
        """A sampled counter series (queue depth, active slots): Perfetto
        renders these as stacked area charts under the track."""
        self._record(TraceEvent(
            name, cat, track, "C", self.clock() if ts is None else ts,
            args=values or None,
        ))

    @contextlib.contextmanager
    def span(self, name: str, cat: str, track: str, *,
             req: int | None = None, **args):
        """Context-manager span on the recorder's clock (host-side work:
        data waits, checkpoint saves). Records even when the body raises
        — the span that blew up is the one the post-mortem wants."""
        t0 = self.clock()
        try:
            yield
        finally:
            self.complete(name, cat, track, ts=t0, dur=self.clock() - t0,
                          req=req, **args)

    # ------------------------------------------------------------- reading

    def events(self) -> list[TraceEvent]:
        """Snapshot copy of the ring, oldest first."""
        with self._lock:
            return list(self._buf)

    def last(self, n: int) -> list[TraceEvent]:
        with self._lock:
            if n >= len(self._buf):
                return list(self._buf)
            return list(self._buf)[-n:]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    # -------------------------------------------------------------- export

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON object (the "JSON Object Format":
        {"traceEvents": [...]}) with thread-name/sort metadata per track
        and one flow per request stitched through its spans."""
        return events_to_chrome(self.events())

    def export_chrome(self, path: str) -> str:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
        return path


def events_to_chrome(events: list[TraceEvent]) -> dict:
    """Convert recorded events to the Chrome trace-event format.

    Timestamps are microseconds relative to the earliest event (Perfetto
    handles absolute monotonic stamps, but small offsets keep the JSON
    readable and diff-able). Each distinct `track` becomes a tid with a
    thread_name/thread_sort_index metadata record; request-bound duration
    events additionally get flow events (`ph` s/t/f, one flow id per
    request) so Perfetto draws arrows across tracks from submit to
    finish."""
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(e.ts for e in events)
    tracks = sorted({e.track for e in events}, key=_track_sort_key)
    tids = {t: i for i, t in enumerate(tracks)}
    out: list[dict] = []
    for track, tid in tids.items():
        out.append({"ph": "M", "pid": 1, "tid": tid, "name": "thread_name",
                    "args": {"name": track}})
        out.append({"ph": "M", "pid": 1, "tid": tid,
                    "name": "thread_sort_index", "args": {"sort_index": tid}})

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    by_req: dict[int, list[TraceEvent]] = {}
    for e in events:
        rec = {"ph": e.ph, "pid": 1, "tid": tids[e.track], "name": e.name,
               "cat": e.cat, "ts": us(e.ts)}
        args = dict(e.args or {})
        if e.ph == "X":
            rec["dur"] = round(e.dur * 1e6, 3)
        elif e.ph == "i":
            rec["s"] = "t"  # thread-scoped instant
        elif e.ph == "C":
            rec["args"] = args
            out.append(rec)
            continue
        if e.req is not None:
            args["req"] = e.req
            by_req.setdefault(e.req, []).append(e)
        if args:
            rec["args"] = args
        out.append(rec)

    # one flow per request: start at its first event, step through every
    # later duration event, finish at its last event — synthesized here so
    # the hot recording path never pays for flow bookkeeping
    for req, evs in by_req.items():
        evs = sorted(evs, key=lambda e: (e.ts, -ord(e.ph[0])))
        for i, e in enumerate(evs):
            ph = "s" if i == 0 else ("f" if i == len(evs) - 1 else "t")
            if len(evs) == 1:
                break
            flow = {"ph": ph, "pid": 1, "tid": tids[e.track],
                    "name": f"req{req}", "cat": "flow", "id": req,
                    "ts": us(e.ts)}
            if ph == "f":
                flow["bp"] = "e"  # bind to the enclosing slice
            out.append(flow)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def fleet_events_to_chrome(sections) -> dict:
    """Stitch N recorders into ONE Chrome trace: `sections` is
    ``[(label, events), ...]`` — the router recorder plus one section
    per replica, all on the shared engine clock (`serve.metrics.now`),
    so one t0 aligns every section.

    Layout: each section becomes its own Perfetto PROCESS (pid = index
    + 1, named via process_name/process_sort_index metadata) with its
    own tracks as tids — the process-per-replica view the fleet drain
    post-mortem reads top-to-bottom. Per-section per-request flows are
    emitted exactly as `events_to_chrome` does (request ids are unique
    across in-process replicas, so the flow ids cannot collide);
    additionally, every event carrying a ``rid`` arg (the router's
    route/reroute/migrate spans and each engine's submit instant) joins
    a CROSS-SECTION flow keyed on the request's trace id — the arrow
    that follows a request from the router into its replica and, after
    a drain, across to the adopting peer. Flow ids are crc32(rid)
    (Chrome binds flows by (cat, name, id), and the name carries the
    full rid, so a crc collision cannot merge two requests' arrows).

    A ``fleet_manifest`` metadata record lists the declared section
    labels. It survives `load_chrome`'s events-only round trip, so
    `summarize_trace` can detect a PARTIAL export (a slice of the
    stitched file missing a declared section) and refuse loudly
    instead of summarizing half a fleet as the whole."""
    import zlib

    sections = [(label, list(evs)) for label, evs in sections]
    labels = [label for label, _ in sections]
    if len(set(labels)) != len(labels):
        raise ValueError(f"duplicate fleet section labels: {labels}")
    out: list[dict] = [{
        "ph": "M", "pid": 0, "tid": 0, "name": "fleet_manifest",
        "args": {"sections": labels},
    }]
    all_ts = [e.ts for _, evs in sections for e in evs]
    t0 = min(all_ts) if all_ts else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    # (pid, ts, tid, rid) anchors for the cross-section flows
    rid_anchors: dict[str, list[tuple[float, int, int]]] = {}
    for idx, (label, evs) in enumerate(sections):
        pid = idx + 1
        out.append({"ph": "M", "pid": pid, "name": "process_name",
                    "args": {"name": label}})
        out.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                    "args": {"sort_index": idx}})
        tracks = sorted({e.track for e in evs}, key=_track_sort_key)
        tids = {t: i for i, t in enumerate(tracks)}
        for track, tid in tids.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": track}})
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_sort_index",
                        "args": {"sort_index": tid}})
        by_req: dict[int, list[TraceEvent]] = {}
        for e in evs:
            rec = {"ph": e.ph, "pid": pid, "tid": tids[e.track],
                   "name": e.name, "cat": e.cat, "ts": us(e.ts)}
            args = dict(e.args or {})
            if e.ph == "X":
                rec["dur"] = round(e.dur * 1e6, 3)
            elif e.ph == "i":
                rec["s"] = "t"
            elif e.ph == "C":
                rec["args"] = args
                out.append(rec)
                continue
            if e.req is not None:
                args["req"] = e.req
                by_req.setdefault(e.req, []).append(e)
            if args:
                rec["args"] = args
            rid = (e.args or {}).get("rid")
            if rid is not None:
                rid_anchors.setdefault(str(rid), []).append(
                    (e.ts, pid, tids[e.track]))
            out.append(rec)
        for req, revs in by_req.items():
            revs = sorted(revs, key=lambda e: (e.ts, -ord(e.ph[0])))
            if len(revs) == 1:
                continue
            for i, e in enumerate(revs):
                ph = "s" if i == 0 else ("f" if i == len(revs) - 1
                                         else "t")
                flow = {"ph": ph, "pid": pid, "tid": tids[e.track],
                        "name": f"req{req}", "cat": "flow", "id": req,
                        "ts": us(e.ts)}
                if ph == "f":
                    flow["bp"] = "e"
                out.append(flow)

    # the cross-section flow: router decision -> replica submit ->
    # (migrate) -> peer submit, joined on the request's trace id
    for rid, anchors in rid_anchors.items():
        if len(anchors) < 2:
            continue
        anchors.sort()
        fid = zlib.crc32(rid.encode())
        for i, (ts, pid, tid) in enumerate(anchors):
            ph = "s" if i == 0 else ("f" if i == len(anchors) - 1
                                     else "t")
            flow = {"ph": ph, "pid": pid, "tid": tid,
                    "name": f"req:{rid}", "cat": "fleet_flow",
                    "id": fid, "ts": us(ts)}
            if ph == "f":
                flow["bp"] = "e"
            out.append(flow)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# --------------------------------------------------------------- anomalies


class AnomalyMonitor:
    """Post-mortem dumper: on an anomaly, append the recorder's last
    `last_n` events plus a metrics snapshot to `path` (JSONL, one record
    per anomaly — crash-safe: each dump opens/fsyncs/closes).

    Triggers (all host-side, O(1) amortized per observation):
      * `observe_finish` — finish reason "timeout" or "cancelled";
      * `observe_reject` — `reject_burst` consecutive rejected
        submissions (one dump per burst; an accepted submission resets);
      * `observe_step` — a step exceeding `slow_step_factor` x the
        rolling median of the last `step_window` step durations (armed
        after `min_steps` observations so compile-warm steps don't trip
        it).

    Past `max_dumps` records the file ROTATES keep-newest: the oldest
    record is rewritten out to make room (atomic tmp + rename, same
    fsync discipline), and the first rotation warns once. A hard cap
    that silently dropped every LATER incident — which is what this
    class did before — buries exactly the dumps a live incident needs:
    the most recent ones. `dumps` counts every dump ever taken; the
    file holds the newest `max_dumps` of them.
    """

    def __init__(
        self,
        recorder: FlightRecorder,
        path: str,
        snapshot_fn: Callable[[], dict] | None = None,
        last_n: int = 256,
        slow_step_factor: float = 10.0,
        step_window: int = 128,
        min_steps: int = 16,
        reject_burst: int = 8,
        max_dumps: int = 64,
        timeseries_fn: Callable[[], dict] | None = None,
    ):
        if slow_step_factor <= 1.0:
            raise ValueError(
                f"slow_step_factor must be > 1, got {slow_step_factor}"
            )
        self.recorder = recorder
        self.path = path
        self.snapshot_fn = snapshot_fn
        # timeseries_fn() -> TimeSeriesStore.doc(): when bound, every
        # dump carries the rolling retrospective — the N-window "what
        # was the engine doing just before this" record
        self.timeseries_fn = timeseries_fn
        self.last_n = last_n
        self.slow_step_factor = slow_step_factor
        self.min_steps = min_steps
        self.reject_burst = reject_burst
        self.max_dumps = max_dumps
        self.dumps = 0
        self._rotation_warned = False
        self._steps: deque[float] = deque(maxlen=step_window)
        self._consec_rejects = 0
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    def observe_step(self, dur_s: float) -> None:
        if len(self._steps) >= self.min_steps:
            med = statistics.median(self._steps)
            if med > 0 and dur_s > self.slow_step_factor * med:
                self.dump("slow_step", step_s=dur_s, median_s=med,
                          factor=round(dur_s / med, 1))
        self._steps.append(dur_s)

    def observe_reject(self) -> None:
        self._consec_rejects += 1
        if self._consec_rejects == self.reject_burst:
            self.dump("reject_burst", consecutive=self._consec_rejects)

    def observe_accept(self) -> None:
        self._consec_rejects = 0

    def observe_finish(self, reason: str) -> None:
        if reason in ("timeout", "cancelled"):
            self.dump(f"finish_{reason}")

    def observe_recompile(self, program: str, new_signatures: int,
                          window_s: float) -> None:
        """A recompile storm (metrics/xla_obs.py CompileRegistry: same
        program, >= storm_k NEW signatures inside the window) — dump the
        ring so the post-mortem shows WHICH requests carried the
        un-bucketed shapes that forced the compiles."""
        self.dump("recompile_storm", program=program,
                  new_signatures=new_signatures, window_s=window_s)

    def dump(self, kind: str, **detail) -> None:
        rec = {
            "kind": kind,
            "ts": self.recorder.clock(),
            "detail": detail,
            "metrics": self.snapshot_fn() if self.snapshot_fn else None,
            "events": [e.to_dict() for e in self.recorder.last(self.last_n)],
        }
        if self.timeseries_fn is not None:
            rec["timeseries"] = self.timeseries_fn()
        line = json.dumps(rec)
        if self.dumps < self.max_dumps:
            with open(self.path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        else:
            # keep-newest rotation: rewrite the file with the oldest
            # record dropped (atomic tmp + replace, so a crash mid-
            # rotation never truncates the JSONL). Anomalies are rare
            # and the file is bounded by max_dumps, so the rewrite cost
            # is noise next to the dump's own event serialization.
            if not self._rotation_warned:
                self._rotation_warned = True
                import warnings

                warnings.warn(
                    f"anomaly dump cap ({self.max_dumps}) reached at "
                    f"{self.path}: rotating keep-newest from here on "
                    "(oldest records drop out)",
                    RuntimeWarning, stacklevel=2,
                )
            try:
                with open(self.path) as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            lines = lines[-(self.max_dumps - 1):] if self.max_dumps > 1 \
                else []
            lines.append(line)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write("\n".join(lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        self.dumps += 1


# ---------------------------------------------------------------- summary

# lifecycle phases in timeline order; the spans partition a request's wall
# time (queue + prefill + decode == finish - submit) by construction
_PHASES = ("queue", "prefill", "decode")

# HTTP front-door phases (serve/api.py, cat "http") in timeline order:
# accept + parse + queue_handoff precede the engine's queue span and
# sse_drain follows its decode span — contiguous stamps on the same
# clock, so http phases + engine phases partition the server-observed
# e2e wall. Joined into per-request rows when present (a PR-8-era or
# direct-submit trace summarizes without them).
_HTTP_PHASES = ("accept", "parse", "queue_handoff", "sse_drain")


def load_chrome(path: str) -> list[dict]:
    """Read a Chrome trace-event JSON ({"traceEvents": [...]} or a bare
    event array) back into a list of event dicts."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict):
        return obj.get("traceEvents", [])
    if isinstance(obj, list):
        return obj
    raise ValueError(f"{path} is not a Chrome trace-event JSON")


def _as_events(trace) -> list[dict]:
    if isinstance(trace, str):
        return load_chrome(trace)
    if isinstance(trace, dict):
        return trace.get("traceEvents", [])
    return list(trace)


def summarize_train_trace(trace) -> dict | None:
    """Aggregate the train-track spans of a `TrainConfig.trace_path`
    export: per-phase counts and total seconds (data_wait / step / eval /
    checkpoint / callback) plus the final goodput record. Returns None
    when the trace holds no train-category events (serve traces go
    through `summarize_trace` instead)."""
    spans: dict[str, dict] = {}
    goodput = None
    found = False
    for e in _as_events(trace):
        if e.get("cat") != "train":
            continue
        found = True
        if e.get("ph") == "X":
            d = spans.setdefault(e["name"], {"count": 0, "total_s": 0.0})
            d["count"] += 1
            d["total_s"] += e.get("dur", 0.0) / 1e6
        elif e.get("name") == "goodput":
            goodput = dict(e.get("args") or {})
    if not found:
        return None
    return {"spans": spans, "goodput": goodput}


def format_train_summary(summary: dict) -> str:
    """Human-readable report for a train trace."""
    lines = ["train trace (no per-request lanes — phases of the fit loop):"]
    for name, d in sorted(summary["spans"].items(),
                          key=lambda kv: -kv[1]["total_s"]):
        lines.append(
            f"  {name:<12} x{d['count']:<5} total {d['total_s']:.4f}s"
        )
    gp = summary["goodput"]
    if gp:
        lines.append(
            f"goodput: {gp.get('goodput')} "
            f"(step {gp.get('step_s')}s / wall {gp.get('wall_s')}s; "
            "first-step compile excluded from the numerator)"
        )
    return "\n".join(lines)


def summarize_trace(trace) -> dict:
    """Rebuild per-request timelines from an exported trace.

    `trace` is a path to a Chrome trace-event JSON, the loaded dict, or a
    list of event dicts. Returns::

        {
          "requests": [  # sorted by total_s descending
            {"req": id, "phases": {"queue": s, "prefill": s, "decode": s},
             "total_s": s, "finish_reason": str|None, "slot": str|None,
             "start_us": us, "tokens": int|None},
            ...
          ],
          "n_requests": N,
          "rejected": count,  # admission-control rejects (no timeline)
          "finish_reasons": {reason: count},
          "phase_totals_s": {phase: total seconds across requests},
        }

    Durations come from the request-category lifecycle spans the engine
    stamps from its own request timestamps, so per-request
    ``sum(phases) == finish_time - submit_time`` — the measured TTFT +
    decode wall time — up to export rounding (µs). Only requests with a
    lifecycle span or finish event get a timeline row: rejected
    submissions are tallied in ``rejected`` (they never held a lane, so
    a zero-phase row would read as a served request the ring lost), and
    bare ``submit`` instants (requests still in flight at export) are
    skipped."""
    events = _as_events(trace)

    reqs: dict[int, dict] = {}

    def entry(rid: int) -> dict:
        return reqs.setdefault(rid, {
            "req": rid, "phases": {}, "total_s": 0.0, "finish_reason": None,
            "slot": None, "start_us": None, "tokens": None,
        })

    rejected = 0
    disconnects = 0
    # http spans collected side-band and attached only to requests that
    # earn a timeline row below — an in-flight request's accept span
    # must not create a zero-phase row of its own
    http_spans: dict[int, dict] = {}
    for e in events:
        args = e.get("args") or {}
        rid = args.get("req")
        if rid is None:
            continue
        if e.get("cat") == "http":
            if e.get("ph") == "X" and e.get("name") in _HTTP_PHASES:
                d = http_spans.setdefault(rid, {})
                d[e["name"]] = (d.get(e["name"], 0.0)
                                + e.get("dur", 0.0) / 1e6)
            elif e.get("name") == "disconnect":
                disconnects += 1
            continue
        if e.get("cat") != "request":
            continue
        if e.get("name") == "reject":
            rejected += 1
            continue
        is_phase = e.get("ph") == "X" and e.get("name") in _PHASES
        if not (is_phase or e.get("name") == "finish"):
            continue  # e.g. a bare "submit" instant: still in flight
        r = entry(rid)
        ts = e.get("ts", 0.0)
        if r["start_us"] is None or ts < r["start_us"]:
            r["start_us"] = ts
        if is_phase:
            dur_s = e.get("dur", 0.0) / 1e6
            r["phases"][e["name"]] = r["phases"].get(e["name"], 0.0) + dur_s
            r["total_s"] += dur_s
            if "tokens" in args:
                r["tokens"] = args["tokens"]
        else:
            r["finish_reason"] = args.get("reason")

    # resolve slot names from thread metadata (tid -> track name)
    tid_names = {
        e.get("tid"): (e.get("args") or {}).get("name")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    for e in events:
        args = e.get("args") or {}
        rid = args.get("req")
        if (rid is not None and e.get("cat") == "request"
                and e.get("ph") == "X" and e.get("name") in ("prefill",
                                                             "decode")):
            name = tid_names.get(e.get("tid"))
            if name and name.startswith("slot"):
                reqs[rid]["slot"] = name

    # join the http phases onto served requests: `e2e_s` is the end-to-
    # end wall (http + engine phases — the partition extended across the
    # HTTP boundary); engine-only rows keep total_s as their whole story
    http_totals = dict.fromkeys(_HTTP_PHASES, 0.0)
    any_http = False
    for rid, hp in http_spans.items():
        r = reqs.get(rid)
        if r is None:
            continue
        any_http = True
        r["http_phases"] = {k: hp[k] for k in _HTTP_PHASES if k in hp}
        r["e2e_s"] = r["total_s"] + sum(hp.values())
        for k, v in hp.items():
            http_totals[k] += v

    ordered = sorted(reqs.values(), key=lambda r: -r["total_s"])
    finish_reasons: dict[str, int] = {}
    phase_totals = dict.fromkeys(_PHASES, 0.0)
    for r in ordered:
        if r["finish_reason"]:
            finish_reasons[r["finish_reason"]] = (
                finish_reasons.get(r["finish_reason"], 0) + 1
            )
        for k, v in r["phases"].items():
            phase_totals[k] = phase_totals.get(k, 0.0) + v
    summary = {
        "requests": ordered,
        "n_requests": len(ordered),
        "rejected": rejected,
        "finish_reasons": finish_reasons,
        "phase_totals_s": phase_totals,
        "programs": _program_roofline(events),
    }
    if any_http:
        # present IFF the trace holds front-door spans — a direct-submit
        # or PR-8-era trace summarizes with the key ABSENT
        summary["http"] = {
            "phase_totals_s": http_totals,
            "disconnects": disconnects,
        }
    mesh = _mesh_section(events)
    if mesh is not None:
        # present IFF the trace holds mesh-observatory events — a PR-4/5
        # era trace summarizes without the key (no invented zeros)
        summary["mesh"] = mesh
    anatomy = _anatomy_section(events)
    if anatomy:
        # present IFF the trace holds compile events carrying the
        # per-op anatomy ledger (xla_obs with the anatomy parse, i.e.
        # any post-PR-13 observatory run) — earlier traces summarize
        # with the key ABSENT, pinned in tests
        summary["anatomy"] = anatomy
    fleet = _fleet_section(events)
    if fleet is not None:
        # present IFF the trace holds fleet events (router spans or the
        # stitched export's manifest) — a single-engine trace
        # summarizes with the key ABSENT, pinned like the mesh section
        summary["fleet"] = fleet
    return summary


def _fleet_section(events: list[dict]) -> dict | None:
    """Rebuild the router's view from a stitched fleet export: the
    declared sections (from the ``fleet_manifest`` metadata record),
    per-replica served-request counts (finish events grouped by
    process), and the routing counters (route/reroute/migrate/drain
    spans, cat "fleet"). None when the trace holds neither a manifest
    nor fleet events — the backward-compat contract for every
    single-engine trace recorded before the fleet fabric existed.

    Raises ValueError on a PARTIAL export: the manifest declares
    sections whose process records are missing (someone sliced the
    stitched file, or an exporter died mid-write past the JSON layer)
    — summarizing half a fleet as the whole would be silent data loss.
    """
    declared: list | None = None
    pid_labels: dict[int, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "fleet_manifest":
            declared = list((e.get("args") or {}).get("sections") or [])
        elif e.get("name") == "process_name":
            label = (e.get("args") or {}).get("name")
            if label is not None:
                pid_labels[e.get("pid")] = label
    routing = {"route": 0, "attempts": 0, "reroutes": 0,
               "migrations": 0, "drains": 0}
    drain_wall_s = 0.0
    migrate_wall_s = 0.0
    migrations: list[dict] = []
    any_fleet = False
    for e in events:
        if e.get("cat") != "fleet":
            continue
        any_fleet = True
        name = e.get("name")
        args = e.get("args") or {}
        if name == "route":
            routing["route"] += 1
            routing["attempts"] += int(args.get("attempts", 1))
        elif name == "reroute":
            routing["reroutes"] += 1
        elif name == "migrate":
            routing["migrations"] += 1
            migrate_wall_s += e.get("dur", 0.0) / 1e6
            migrations.append({
                "rid": args.get("rid"),
                "from": args.get("src"),
                "to": args.get("dst"),
            })
        elif name == "drain":
            routing["drains"] += 1
            drain_wall_s += e.get("dur", 0.0) / 1e6
    if declared is None and not any_fleet:
        return None
    if declared is not None:
        observed = set(pid_labels.values())
        missing = [s for s in declared if s not in observed]
        if missing:
            raise ValueError(
                f"partial fleet export: manifest declares sections "
                f"{declared} but the trace is missing {missing} — "
                "refusing to summarize a slice of the fleet as the "
                "whole")
    # served requests per replica process (finish events carry the
    # authoritative per-request outcome; pid 1 is the router section
    # in a stitched export and never stamps request-cat events)
    by_replica: dict[str, int] = {}
    for e in events:
        if e.get("cat") == "request" and e.get("name") == "finish":
            label = pid_labels.get(e.get("pid"))
            if label is not None:
                by_replica[label] = by_replica.get(label, 0) + 1
    out: dict = {"routing": routing}
    if declared is not None:
        out["sections"] = declared
    if by_replica:
        out["requests_by_replica"] = dict(sorted(by_replica.items()))
    if routing["drains"]:
        out["drain_wall_s"] = round(drain_wall_s, 6)
    if migrations:
        out["migrate_wall_s"] = round(migrate_wall_s, 6)
        out["migrations"] = migrations
    return out


def _anatomy_section(events: list[dict]) -> dict:
    """Per-program anatomy ledgers from the compile events' `anatomy`
    args (metrics/hlo_cost.parse_hlo_costs output, recorded when the
    engine ran with trace + xla_obs): {program: ledger} keeping the
    heaviest-bytes signature per program — the collective-ledger
    convention. Empty dict when no compile event carries one."""
    from solvingpapers_tpu.metrics.hlo_cost import best_anatomy

    candidates: dict[str, list] = {}
    for e in events:
        if e.get("cat") != "xla" or e.get("name") != "compile":
            continue
        args = e.get("args") or {}
        prog = args.get("program")
        if prog and args.get("anatomy"):
            candidates.setdefault(prog, []).append(args["anatomy"])
    out = {}
    for prog, cands in candidates.items():
        best = best_anatomy(cands)
        if best is not None:
            out[prog] = best
    return out


def _mesh_section(events: list[dict]) -> dict | None:
    """Rebuild the mesh observatory's view from an exported trace: the
    per-stage tick timeline (spans on `stage<N>` tracks, cat "mesh"),
    the last `bubble_report` instant, and the collective ledger (compile
    events carrying `comm_*` args — recorded when the engine ran with
    mesh_obs + trace on). None when the trace holds none of the three —
    the backward-compat contract for traces recorded before the mesh
    observatory existed."""
    tid_names = {
        e.get("tid"): (e.get("args") or {}).get("name")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    stages: dict[str, dict] = {}
    bubble: dict | None = None
    comm: dict[str, dict] = {}
    for e in events:
        cat = e.get("cat")
        if cat == "mesh":
            if e.get("name") == "bubble_report" and e.get("ph") == "i":
                bubble = dict(e.get("args") or {})
            elif e.get("ph") == "X":
                track = tid_names.get(e.get("tid")) or ""
                if not track.startswith("stage"):
                    continue
                d = stages.setdefault(track, {
                    "ticks": 0, "fwd": 0, "bwd": 0, "bubble": 0,
                    "busy_s": 0.0, "bubble_s": 0.0,
                })
                dur_s = e.get("dur", 0.0) / 1e6
                d["ticks"] += 1
                name = e.get("name", "")
                if name == "bubble":
                    d["bubble"] += 1
                    d["bubble_s"] += dur_s
                else:
                    d["busy_s"] += dur_s
                    if name.startswith("B"):
                        d["bwd"] += 1
                    else:
                        d["fwd"] += 1
        elif cat == "xla" and e.get("name") == "compile":
            args = e.get("args") or {}
            if not args.get("comm_ops"):
                continue
            prog = args.get("program")
            if not prog:
                continue
            c = comm.setdefault(prog, {"ops": 0, "bytes": 0, "by_type": {}})
            # the largest-traffic signature stands for the program (the
            # collective_stats convention)
            if args.get("comm_bytes", 0) >= c["bytes"]:
                c["ops"] = args.get("comm_ops", 0)
                c["bytes"] = args.get("comm_bytes", 0)
                c["by_type"] = dict(args.get("comm_by_type") or {})
    if not stages and bubble is None and not comm:
        return None
    out: dict = {}
    if stages:
        out["stages"] = {
            k: {**v, "busy_s": round(v["busy_s"], 6),
                "bubble_s": round(v["bubble_s"], 6)}
            for k, v in sorted(stages.items(), key=lambda kv: kv[0])
        }
    if bubble is not None:
        out["bubble"] = bubble
    if comm:
        out["comm"] = comm
    return out


def _program_roofline(events: list[dict]) -> dict:
    """Join the compile registry's `compile` instants (cat "xla",
    carrying cost_analysis flops/bytes per program — recorded when the
    engine runs with BOTH `trace` and `xla_obs` on) against the measured
    per-program spans sharing the program's name, yielding the offline
    per-program roofline: achieved FLOP/s, arithmetic intensity, and —
    when the recording host knew its chip peak — MFU. Empty dict when
    the trace holds no compile events (plain PR-4 traces summarize
    unchanged)."""
    compiles: dict[str, dict] = {}
    for e in events:
        if e.get("cat") != "xla" or e.get("name") != "compile":
            continue
        args = e.get("args") or {}
        prog = args.get("program")
        if not prog:
            continue
        d = compiles.setdefault(prog, {
            "compilations": 0, "compile_time_s": 0.0, "flops_per_call": 0.0,
            "bytes_per_call": 0.0, "peak_flops": None,
        })
        d["compilations"] += 1
        # cached=1 events carry the ORIGINAL executable's compile time
        # (served from the process-global cache — this run compiled
        # nothing), so only cold compiles count toward the wall total,
        # matching the live registry's compile/time_s
        if not args.get("cached"):
            d["compile_time_s"] += args.get("compile_s", 0.0)
        # signatures differ in cost; keep the largest as the per-call
        # bound (the engine's steady-state program for that name)
        d["flops_per_call"] = max(d["flops_per_call"],
                                  args.get("flops", 0.0))
        d["bytes_per_call"] = max(d["bytes_per_call"],
                                  args.get("bytes", 0.0))
        if args.get("peak_flops"):
            d["peak_flops"] = args["peak_flops"]
    if not compiles:
        return {}
    # one fused decode program advances every lane together, and the
    # engine stamps one span PER ACTIVE SLOT sharing the program's wall
    # time (same ts, same dur) — dedupe by (name, ts) so a program call
    # counts once, matching the live registry's calls/run seconds
    seen: set = set()
    for e in events:
        if e.get("ph") != "X" or e.get("name") not in compiles:
            continue
        key = (e["name"], e.get("ts"))
        if key in seen:
            continue
        seen.add(key)
        d = compiles[e["name"]]
        d["calls"] = d.get("calls", 0) + 1
        d["total_s"] = d.get("total_s", 0.0) + e.get("dur", 0.0) / 1e6
    out = {}
    for prog, d in compiles.items():
        calls, total_s = d.get("calls", 0), d.get("total_s", 0.0)
        row = {
            "compilations": d["compilations"],
            "compile_time_s": round(d["compile_time_s"], 6),
            "calls": calls,
            "total_s": round(total_s, 6),
            "flops_per_call": d["flops_per_call"],
            "bytes_per_call": d["bytes_per_call"],
        }
        if calls and total_s > 0 and d["flops_per_call"] > 0:
            achieved = d["flops_per_call"] * calls / total_s
            row["achieved_flops_per_s"] = achieved
            if d["bytes_per_call"] > 0:
                row["intensity_flops_per_byte"] = (
                    d["flops_per_call"] / d["bytes_per_call"]
                )
            if d["peak_flops"]:
                row["mfu"] = achieved / d["peak_flops"]
        out[prog] = row
    return out


def format_summary(summary: dict, top: int = 5) -> str:
    """Human-readable report for `cli trace-summary`: phase breakdown
    totals, then the `top` slowest requests with per-phase timings."""
    lines = [f"requests: {summary['n_requests']}"]
    if summary.get("rejected"):
        lines.append(f"rejected submissions: {summary['rejected']}")
    if summary["finish_reasons"]:
        reasons = ", ".join(
            f"{k}={v}" for k, v in sorted(summary["finish_reasons"].items())
        )
        lines.append(f"finish reasons: {reasons}")
    totals = summary["phase_totals_s"]
    grand = sum(totals.values())
    if grand > 0:
        parts = "  ".join(
            f"{k}={v:.4f}s ({100 * v / grand:.1f}%)"
            for k, v in totals.items()
        )
        lines.append(f"phase totals: {parts}")
    lines.append("")
    lines.append(f"slowest {min(top, summary['n_requests'])} requests "
                 "(total = queue + prefill + decode):")
    header = (f"  {'req':>6} {'total_s':>9} {'queue_s':>9} {'prefill_s':>9} "
              f"{'decode_s':>9} {'slot':>6}  reason")
    lines.append(header)
    for r in summary["requests"][:top]:
        ph = r["phases"]
        lines.append(
            f"  {r['req']:>6} {r['total_s']:>9.4f} "
            f"{ph.get('queue', 0.0):>9.4f} {ph.get('prefill', 0.0):>9.4f} "
            f"{ph.get('decode', 0.0):>9.4f} {str(r['slot'] or '-'):>6}  "
            f"{r['finish_reason'] or '-'}"
        )
    http = summary.get("http")
    if http:
        totals = http["phase_totals_s"]
        parts = "  ".join(f"{k}={totals[k]:.4f}s" for k in _HTTP_PHASES)
        lines.append("")
        lines.append(f"http front door: {parts}")
        if http.get("disconnects"):
            lines.append(f"  disconnects: {http['disconnects']}")
    roofline = format_roofline(summary.get("programs") or {})
    if roofline:
        lines.append("")
        lines.append(roofline)
    from solvingpapers_tpu.metrics.hlo_cost import format_anatomy

    anatomy = format_anatomy(summary.get("anatomy") or {})
    if anatomy:
        lines.append("")
        lines.append(anatomy)
    mesh = format_mesh(summary.get("mesh"))
    if mesh:
        lines.append("")
        lines.append(mesh)
    fleet = format_fleet(summary.get("fleet"))
    if fleet:
        lines.append("")
        lines.append(fleet)
    return "\n".join(lines)


def format_fleet(fleet: dict | None) -> str:
    """Human-readable fleet report (the `fleet` section of
    `summarize_trace`), or "" when the trace held no fleet events."""
    if not fleet:
        return ""
    lines: list[str] = []
    sections = fleet.get("sections")
    if sections:
        lines.append(f"fleet: {len(sections)} sections "
                     f"({', '.join(sections)})")
    else:
        lines.append("fleet: router events present")
    r = fleet["routing"]
    lines.append(
        f"  routing: {r['route']} routed ({r['attempts']} attempts, "
        f"{r['reroutes']} reroutes)  drains={r['drains']}  "
        f"migrations={r['migrations']}"
    )
    by_rep = fleet.get("requests_by_replica")
    if by_rep:
        parts = "  ".join(f"{k}={v}" for k, v in by_rep.items())
        lines.append(f"  requests finished by replica: {parts}")
    if fleet.get("drain_wall_s") is not None:
        lines.append(f"  drain wall: {fleet['drain_wall_s']:.4f}s")
    for m in fleet.get("migrations") or []:
        lines.append(
            f"  migrated {m.get('rid')}: {m.get('from')} -> "
            f"{m.get('to')}"
        )
    return "\n".join(lines)


def format_mesh(mesh: dict | None) -> str:
    """Human-readable mesh-observatory report (the `mesh` section of
    `summarize_trace`), or "" when the trace held no mesh events."""
    if not mesh:
        return ""
    lines: list[str] = []
    bubble = mesh.get("bubble")
    if bubble:
        lines.append(
            f"pipeline bubble report ({bubble.get('schedule')}, "
            f"{bubble.get('n_devices')} stages x "
            f"{bubble.get('n_microbatches')} microbatches):"
        )
        frac = [f"analytic={bubble.get('analytic_bubble_fraction')}"]
        if bubble.get("predicted_bubble_fraction") is not None:
            frac.append(f"predicted={bubble['predicted_bubble_fraction']}")
        if bubble.get("measured_bubble_fraction") is not None:
            frac.append(f"measured={bubble['measured_bubble_fraction']}")
        lines.append("  bubble fraction: " + "  ".join(frac))
        lines.append(
            f"  straggler: stage{bubble.get('straggler_stage')} "
            f"(imbalance {bubble.get('imbalance')}x mean; per-stage probe "
            f"{bubble.get('stage_s')}s)"
        )
    stages = mesh.get("stages")
    if stages:
        lines.append("per-stage tick timeline (derived from fenced steps):")
        lines.append(
            f"  {'stage':<8} {'ticks':>6} {'fwd':>5} {'bwd':>5} "
            f"{'bubble':>7} {'busy_s':>9} {'bubble_s':>9}"
        )
        for name, d in stages.items():
            lines.append(
                f"  {name:<8} {d['ticks']:>6} {d['fwd']:>5} {d['bwd']:>5} "
                f"{d['bubble']:>7} {d['busy_s']:>9.4f} "
                f"{d['bubble_s']:>9.4f}"
            )
    comm = mesh.get("comm")
    if comm:
        lines.append("collective ledger (static per-call counts, "
                     "output-shape bytes):")
        lines.append(f"  {'program':<18} {'ops':>5} {'bytes':>12}  by type")
        for prog, d in sorted(comm.items(), key=lambda kv: -kv[1]["bytes"]):
            kinds = ", ".join(
                f"{k}x{v.get('ops', 0)}"
                for k, v in sorted(d.get("by_type", {}).items())
            )
            lines.append(
                f"  {prog:<18} {d['ops']:>5} {d['bytes']:>12}  {kinds}"
            )
    return "\n".join(lines)


def format_roofline(programs: dict) -> str:
    """Human-readable per-program roofline table (the `programs` section
    of `summarize_trace`), or "" when the trace held no compile events.
    Programs with no same-named measured span (splice/extract/train
    programs — their spans aggregate multiple calls under other names)
    show compile info with '-' for the measured columns."""
    if not programs:
        return ""
    lines = ["per-program roofline (compile registry x measured spans):"]
    lines.append(
        f"  {'program':<18} {'calls':>6} {'total_s':>9} "
        f"{'compile_s':>10} {'GFLOP/s':>9} {'flops/B':>8} {'mfu':>7}"
    )
    for prog, d in sorted(programs.items(),
                          key=lambda kv: -kv[1].get("total_s", 0.0)):
        gflops = d.get("achieved_flops_per_s")
        inten = d.get("intensity_flops_per_byte")
        mfu_v = d.get("mfu")
        lines.append(
            f"  {prog:<18} {d.get('calls', 0):>6} "
            f"{d.get('total_s', 0.0):>9.4f} "
            f"{d['compile_time_s']:>10.4f} "
            f"{(f'{gflops / 1e9:.2f}' if gflops else '-'):>9} "
            f"{(f'{inten:.2f}' if inten else '-'):>8} "
            f"{(f'{mfu_v:.4f}' if mfu_v is not None else '-'):>7}"
        )
    return "\n".join(lines)
