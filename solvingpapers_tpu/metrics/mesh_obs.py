"""Mesh observatory: collective-traffic ledger, pipeline-bubble
diagnosis, and per-device trace tracks for the sharding stack.

The flight recorder (metrics/trace.py) sees host wall time and the
compile observatory (metrics/xla_obs.py) sees single-program cost; a
sharded engine fails in ways neither can name — a TP program whose
all-reduces eat the step, a pipeline whose straggler stage doubles the
bubble, a per-device HBM projection booked at global bytes. This module
is the mesh-aware layer over both (MegaScale-style straggler/bubble
diagnosis, Orca-style per-iteration accounting), built BEFORE the serve
engine is sharded so multi-device regressions land debuggable:

* **Collective ledger** — `parse_hlo_collectives` counts and sizes the
  `all-reduce` / `all-gather` / `reduce-scatter` / `all-to-all` /
  `collective-permute` ops in a compiled program's HLO text
  (`compiled.as_text()`); the `CompileRegistry` runs it per compilation
  when built with `collectives=True`, so every program the engines
  dispatch carries its comm-bytes-per-call. Static counts: an op inside
  a `while` body (a lax.scan schedule) is counted once, not per trip —
  the ledger answers "which programs talk, how much, over which
  collective kinds", not cycle-exact traffic. Bytes are the op's OUTPUT
  shape bytes (the gathered/reduced tensor), a uniform proxy across
  kinds. Joined with the registry's fenced per-call wall seconds and a
  chip's ICI bandwidth (`link_bandwidth_bytes_per_s`, NaN-sentinel on
  CPU/unknown like `chip_peak_flops`), it projects a per-program link
  time and the gap to the measured wall.

* **Pipeline-bubble diagnosis** — `probe_stage_costs` measures each
  pipeline stage_fn standalone (forward, or forward+backward for
  training schedules: the backward unit's cost mirrors 1F1B's
  vjp-of-recompute); `bubble_report` combines the probed per-stage
  seconds with the schedule algebra (sharding/pipeline.py
  `schedule_ticks` / `analytic_bubble_fraction`) into: the analytic
  balanced bubble fraction (S-1)/(M+S-1), a predicted fraction that
  folds in the probed imbalance (every tick costs the slowest stage —
  the schedules are ppermute-lockstep), the straggler stage, and — when
  a fenced step wall is supplied — the measured fraction
  1 - useful_work / (devices * wall).

* **Mesh trace tracks** — with a `FlightRecorder` attached,
  `MeshObservatory.observe_step` stamps one span per (stage, tick) on
  `stage<N>` tracks, labeled F<i>/B<i>/bubble from the schedule algebra
  and spread across the FENCED step wall (derived spans: the host
  cannot see intra-program tick boundaries without a device profiler;
  the labels are exact, the per-tick durations are wall/ticks). The
  bubble report is recorded as a `bubble_report` instant so
  `summarize_trace` / `cli trace-summary` can rebuild the diagnosis
  offline.

Everything is opt-in (`TrainConfig.mesh_obs`); off means no
MeshObservatory exists and no `mesh/*` gauge is ever emitted —
the same None-recorder contract as tracing.
"""

from __future__ import annotations

import dataclasses
import math
import re
import time
import warnings
from typing import Callable, Sequence

import jax

from solvingpapers_tpu.metrics.writer import PrometheusTextWriter
from solvingpapers_tpu.sharding.pipeline import (
    analytic_bubble_fraction,
    schedule_ticks,
    tick_unit,
)

# --------------------------------------------------- collective ledger

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# "= <output shape(s)> <collective>(" — defining occurrences only:
# operand references sit inside the parens of another op's definition
# and are never directly preceded by "= <shape>"; async pairs count at
# the -start (the -done carries no new traffic); alternation order puts
# longer names first so "all-reduce" never half-matches "all-reduce-s…".
_COLL_RE = re.compile(
    r"=\s*(?P<out>\([^)]*\)|\S+)\s+"
    r"(?P<op>reduce-scatter|all-reduce|all-gather|all-to-all|"
    r"collective-permute)"
    r"(?:-start)?\("
)

_SHAPE_RE = re.compile(r"(?P<dt>[a-z]\d*[a-z0-9]*|pred)\[(?P<dims>[\d,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1, "f8e3m4": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _shape_atom_bytes(dt: str, dims: str) -> int:
    nbytes = _DTYPE_BYTES.get(dt)
    if nbytes is None:
        # fall back to the trailing bit-width (f8..., s4, u2, token-free)
        digits = re.search(r"(\d+)$", dt)
        nbytes = max(int(digits.group(1)) // 8, 1) if digits else 4
    n = 1
    if dims:
        for d in dims.split(","):
            n *= int(d)
    return n * nbytes


def parse_hlo_collectives(hlo_text: str) -> dict:
    """Count and size the collective ops defined in an HLO module's text.

    Returns ``{"ops": N, "bytes": B, "by_type": {kind: {"ops": n,
    "bytes": b}}}`` — empty counts (``ops == 0``) for a program with no
    collectives (the single-device case), which is a true zero, not an
    absence. Bytes are output-shape bytes per op (tuple outputs summed);
    ops inside while bodies count once (see the module docstring).
    """
    by_type: dict[str, dict[str, int]] = {}
    total_ops = 0
    total_bytes = 0
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        nbytes = sum(
            _shape_atom_bytes(s.group("dt"), s.group("dims"))
            for s in _SHAPE_RE.finditer(m.group("out"))
        )
        kind = m.group("op")
        d = by_type.setdefault(kind, {"ops": 0, "bytes": 0})
        d["ops"] += 1
        d["bytes"] += nbytes
        total_ops += 1
        total_bytes += nbytes
    return {"ops": total_ops, "bytes": total_bytes, "by_type": by_type}


# aggregate per-chip ICI bandwidth in bytes/s (public spec sheets,
# bidirectional across all links — planning numbers for projecting link
# time, same table-or-NaN contract as metrics.mfu.chip_peak_flops)
_ICI_BYTES_PER_S = {
    "v4": 300e9,      # 2.4 Tbps
    "v5 lite": 200e9,  # 1.6 Tbps
    "v5e": 200e9,
    "v5": 600e9,      # v5p, 4.8 Tbps
    "v5p": 600e9,
    "v6 lite": 448e9,  # 3.584 Tbps
    "v6e": 448e9,
}

_warned_kinds: set[str] = set()


def link_bandwidth_bytes_per_s(device=None) -> float:
    """Aggregate ICI bytes/s for `device`, or NaN when unknown (CPU
    hosts, unlisted chips) — the NaN propagates into an ABSENT link-time
    gauge, never a mis-scaled one (the chip_peak_flops contract)."""
    device = device or jax.devices()[0]
    kind = str(getattr(device, "device_kind", "") or "").lower()
    for key, val in _ICI_BYTES_PER_S.items():
        if key in kind:
            return val
    if kind not in _warned_kinds:
        _warned_kinds.add(kind)
        warnings.warn(
            f"link_bandwidth_bytes_per_s: unrecognized device_kind "
            f"{kind!r}; returning NaN — link-time gauges will be omitted "
            "(extend metrics.mesh_obs._ICI_BYTES_PER_S for new chips)",
            stacklevel=2,
        )
    return float("nan")


# ------------------------------------------------- pipeline stage probe


def probe_stage_costs(
    stage_params,
    x,
    stage_fn,
    *,
    train: bool = False,
    reps: int = 3,
    clock: Callable[[], float] = time.monotonic,
) -> list[float]:
    """Measure each pipeline stage standalone: seconds per microbatch
    unit, per stage.

    `stage_params` is the stacked pytree (leading dim = number of
    storage rows); `x` one microbatch-shaped activation; `stage_fn`
    either one callable `(params, x) -> y` (the SPMD schedules' uniform
    stage) or a sequence of per-stage callables (heterogeneous probes).
    With `train=True` the probed unit is forward PLUS
    grad-of-recompute — the cost shape of 1F1B's F unit + B unit (the B
    unit re-runs the stage forward from its stashed input before the
    vjp). Each variant jits once and is timed fenced over `reps` runs
    (min — the schedule's lockstep tick is gated by compute, not by
    scheduling noise).
    """
    n_rows = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    fns = (
        list(stage_fn) if isinstance(stage_fn, Sequence) else
        [stage_fn] * n_rows
    )
    if len(fns) != n_rows:
        raise ValueError(
            f"{len(fns)} stage fns for {n_rows} stage rows"
        )

    import jax.numpy as jnp

    def unit_of(fn):
        if not train:
            return fn

        def unit(p, xx):
            y = fn(p, xx)  # the F unit

            def scalar(p):  # the B unit: recompute forward, then vjp
                yy = fn(p, xx)
                return jnp.sum(yy.astype(jnp.float32) ** 2)

            return y, jax.grad(scalar)(p)

        return unit

    costs: list[float] = []
    jitted_cache: dict[int, Callable] = {}
    for s in range(n_rows):
        p_s = jax.tree_util.tree_map(lambda a: a[s], stage_params)
        jitted = jitted_cache.get(id(fns[s]))
        if jitted is None:
            jitted = jax.jit(unit_of(fns[s]))
            jitted_cache[id(fns[s])] = jitted
        jax.block_until_ready(jitted(p_s, x))  # compile outside the timing
        best = math.inf
        for _ in range(max(reps, 1)):
            t0 = clock()
            jax.block_until_ready(jitted(p_s, x))
            best = min(best, clock() - t0)
        costs.append(best)
    return costs


def bubble_report(
    stage_s: Sequence[float],
    n_microbatches: int,
    *,
    n_devices: int | None = None,
    schedule: str = "gpipe",
    measured_step_s: float | None = None,
) -> dict:
    """Combine probed per-stage unit seconds with the schedule algebra.

    The schedules are ppermute-lockstep: every tick lasts as long as the
    slowest stage, so with probed unit costs t_s the predicted pass wall
    is (M·v + P - 1) · max(t) and the waste fraction (bubble + imbalance)
    is ``1 - useful / capacity`` with useful = M · Σt and capacity =
    P · wall. For balanced stages that reduces exactly to the analytic
    (P-1)/(M·v+P-1). `measured_step_s` (a fenced step wall covering one
    pipeline pass) yields the measured fraction on the same definition.
    `stage_s` has one entry per STORAGE ROW (P·v rows under the
    interleaved schedule); `n_devices` defaults to the row count.
    """
    rows = len(stage_s)
    if rows == 0:
        raise ValueError("stage_s is empty")
    n_dev = n_devices or rows
    if rows % n_dev:
        raise ValueError(f"{rows} stage rows not divisible by {n_dev} devices")
    n_virtual = rows // n_dev
    t_max = max(stage_s)
    t_sum = sum(stage_s)
    t_mean = t_sum / rows
    unit_ticks = n_microbatches * n_virtual + n_dev - 1
    predicted_step_s = unit_ticks * t_max
    useful_s = n_microbatches * t_sum
    report = {
        "schedule": schedule,
        "n_devices": n_dev,
        "n_virtual": n_virtual,
        "n_microbatches": n_microbatches,
        "stage_s": [round(t, 6) for t in stage_s],
        "straggler_stage": int(max(range(rows), key=lambda i: stage_s[i])),
        "imbalance": round(t_max / t_mean, 4) if t_mean > 0 else 1.0,
        "analytic_bubble_fraction": round(
            analytic_bubble_fraction(n_microbatches, n_dev, n_virtual), 4
        ),
        "predicted_bubble_fraction": round(
            1.0 - useful_s / (n_dev * predicted_step_s), 4
        ) if predicted_step_s > 0 else 0.0,
        "predicted_step_s": round(predicted_step_s, 6),
    }
    if measured_step_s is not None and measured_step_s > 0:
        report["measured_step_s"] = round(measured_step_s, 6)
        report["measured_bubble_fraction"] = round(
            1.0 - useful_s / (n_dev * measured_step_s), 4
        )
    return report


# ----------------------------------------------------- mesh observatory


@dataclasses.dataclass(frozen=True)
class PipelineScheduleInfo:
    """What the observatory needs to label ticks: devices on the pipe
    axis, microbatches per pass, virtual slices per device, schedule
    kind ("gpipe" | "1f1b")."""

    n_stages: int
    n_microbatches: int
    n_virtual: int = 1
    schedule: str = "gpipe"

    @property
    def ticks(self) -> int:
        return schedule_ticks(self.n_microbatches, self.n_stages,
                              self.n_virtual, self.schedule)


class MeshObservatory:
    """Aggregates the mesh-side signals into `mesh/*` gauges, a
    /statusz section, and mesh trace tracks.

    `registry` (a CompileRegistry built with `collectives=True`)
    supplies the collective ledger; `schedule` + `set_stage_probe`
    supply the bubble diagnosis; `trace` (a FlightRecorder or None —
    the None-recorder pattern) receives per-tick stage spans and the
    bubble-report instant. `observe_step` expects FENCED step walls
    (the engine only fences in observability modes). Per-tick span
    synthesis is capped at `max_step_traces` steps so a long run's ring
    holds the interesting window without paying O(stages·ticks) host
    appends forever.
    """

    def __init__(
        self,
        mesh=None,
        registry=None,
        trace=None,
        schedule: PipelineScheduleInfo | None = None,
        link_bandwidth: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        max_step_traces: int = 64,
    ):
        self.mesh = mesh
        self.registry = registry
        self.trace = trace
        self.schedule = schedule
        self.link_bw = (
            link_bandwidth if link_bandwidth is not None
            else link_bandwidth_bytes_per_s()
        )
        self.clock = clock
        self.max_step_traces = max_step_traces
        self.n_devices = (
            int(mesh.devices.size) if mesh is not None else len(jax.devices())
        )
        self._stage_probe: dict | None = None  # set_stage_probe kwargs
        self._last_step_s: float | None = None
        self._steps_traced = 0
        self._report_emitted = False

    # ------------------------------------------------------------ inputs

    def attach_trace(self, trace) -> None:
        """Re-point the observatory at a run's recorder (or None). The
        engines build one FlightRecorder per fit()/run but keep the
        observatory across runs — without re-attaching, a second run's
        mesh events would land in the first run's dead ring. Resets the
        per-run span-synthesis cap and the report-emitted latch."""
        self.trace = trace
        self._steps_traced = 0
        self._report_emitted = False

    def set_stage_probe(self, stage_s: Sequence[float],
                        n_microbatches: int) -> None:
        """Attach probed per-stage unit seconds (probe_stage_costs);
        the bubble report is recomputed on read against the newest
        fenced step wall."""
        self._stage_probe = {
            "stage_s": list(stage_s),
            "n_microbatches": n_microbatches,
        }
        self._report_emitted = False

    def observe_step(self, ts: float, dur_s: float, steps: int = 1) -> None:
        """One fenced dispatch: `ts` start on the observatory clock,
        `dur_s` wall, `steps` train steps inside (a scan window). Feeds
        the measured bubble fraction and, with a recorder and schedule
        attached, stamps per-tick spans on the stage tracks."""
        per_step = dur_s / max(steps, 1)
        self._last_step_s = per_step
        if self._stage_probe is not None and not self._report_emitted \
                and self.trace is not None:
            report = self.bubble_report()
            if report is not None:
                self._report_emitted = True
                self.trace.instant("bubble_report", "mesh", "mesh", **report)
        if self.trace is None or self.schedule is None:
            return
        # clamp INSIDE the window too: one scan dispatch can carry many
        # steps, and synthesizing all of them would blow the cap by a
        # whole window (steps x stages x ticks ring appends)
        todo = min(max(steps, 1), self.max_step_traces - self._steps_traced)
        if todo <= 0:
            return
        self._steps_traced += todo
        info = self.schedule
        ticks = info.ticks
        tick_s = dur_s / (ticks * max(steps, 1))
        for k in range(todo):
            t0 = ts + k * per_step
            for d in range(info.n_stages):
                for t in range(ticks):
                    self.trace.complete(
                        tick_unit(t, d, info.n_microbatches, info.n_stages,
                                  info.n_virtual, info.schedule),
                        "mesh", f"stage{d}",
                        ts=t0 + t * tick_s, dur=tick_s, tick=t,
                    )

    # ----------------------------------------------------------- reading

    def bubble_report(self) -> dict | None:
        """The pipeline-bubble diagnosis, or None before a stage probe
        ran (never invented)."""
        if self._stage_probe is None:
            return None
        sched = self.schedule
        return bubble_report(
            self._stage_probe["stage_s"],
            self._stage_probe["n_microbatches"],
            n_devices=sched.n_stages if sched is not None else None,
            schedule=sched.schedule if sched is not None else "gpipe",
            measured_step_s=self._last_step_s,
        )

    def comm(self) -> dict:
        """Per-program collective ledger joined with measured walls:
        {program: {ops, bytes, by_type, calls, run_s[, link_s, gap_s]}}.
        Empty when no registry is attached or nothing compiled yet."""
        if self.registry is None:
            return {}
        stats = self.registry.collective_stats()
        for d in stats.values():
            if math.isfinite(self.link_bw) and self.link_bw > 0:
                d["link_s"] = d["bytes"] / self.link_bw
                if d.get("calls"):
                    d["gap_s"] = d["run_s"] / d["calls"] - d["link_s"]
        return stats

    def gauges(self) -> dict[str, float]:
        """Flat `mesh/*` metric keys (the log-row / ServeMetrics
        gauge-provider shape). Present iff the observatory exists —
        the key-surface contract mirroring `mem/*` / `compile/*`."""
        out: dict[str, float] = {"mesh/devices": float(self.n_devices)}
        comm = self.comm()
        if self.registry is not None:
            with_coll = {k: v for k, v in comm.items() if v["ops"]}
            out["mesh/comm_programs"] = float(len(with_coll))
            out["mesh/comm_ops"] = float(
                sum(v["ops"] for v in comm.values())
            )
            out["mesh/comm_bytes_per_step"] = float(
                sum(v["bytes"] for v in comm.values())
            )
            by_type: dict[str, dict[str, int]] = {}
            for v in comm.values():
                for kind, kd in v["by_type"].items():
                    agg = by_type.setdefault(kind, {"ops": 0, "bytes": 0})
                    agg["ops"] += kd["ops"]
                    agg["bytes"] += kd["bytes"]
            for kind, kd in by_type.items():
                name = PrometheusTextWriter.sanitize(kind)
                out[f"mesh/comm_{name}_ops"] = float(kd["ops"])
                out[f"mesh/comm_{name}_bytes"] = float(kd["bytes"])
            for prog, v in with_coll.items():
                name = PrometheusTextWriter.sanitize(prog)
                out[f"mesh/comm_{name}_bytes"] = float(v["bytes"])
                if "link_s" in v:
                    out[f"mesh/comm_{name}_link_s"] = float(v["link_s"])
                if "gap_s" in v:
                    out[f"mesh/comm_{name}_gap_s"] = float(v["gap_s"])
        report = self.bubble_report()
        if report is not None:
            out["mesh/bubble_fraction_analytic"] = float(
                report["analytic_bubble_fraction"]
            )
            out["mesh/bubble_fraction_predicted"] = float(
                report["predicted_bubble_fraction"]
            )
            if "measured_bubble_fraction" in report:
                out["mesh/bubble_fraction_measured"] = float(
                    report["measured_bubble_fraction"]
                )
            out["mesh/straggler_stage"] = float(report["straggler_stage"])
            out["mesh/stage_imbalance"] = float(report["imbalance"])
            for d, t in enumerate(report["stage_s"]):
                out[f"mesh/stage{d}_probe_s"] = float(t)
        if self._last_step_s is not None:
            out["mesh/step_wall_s"] = float(self._last_step_s)
        return out

    def snapshot(self) -> dict:
        """Structured view for /statusz."""
        snap: dict = {"devices": self.n_devices}
        if self.mesh is not None:
            from solvingpapers_tpu.sharding.mesh import mesh_axis_sizes

            snap["mesh_axes"] = {
                k: int(v) for k, v in mesh_axis_sizes(self.mesh).items()
            }
        if math.isfinite(self.link_bw):
            snap["link_bandwidth_bytes_per_s"] = self.link_bw
        comm = self.comm()
        if comm:
            snap["comm"] = comm
        report = self.bubble_report()
        if report is not None:
            snap["bubble"] = report
        if self._last_step_s is not None:
            snap["step_wall_s"] = self._last_step_s
        return snap
