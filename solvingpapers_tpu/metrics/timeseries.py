"""Rolling in-process time series: a fixed-budget ring of periodic
metric snapshots — the "what was the engine doing just before X"
record.

`ServeMetrics.snapshot()` answers "what are the totals NOW";
`FlightRecorder` answers "what did THIS request/step do". Neither
answers the incident-review question "what did throughput, queue depth
and tail latency look like over the two minutes BEFORE the quarantine"
— by the time anyone looks, the counters have moved on and the ring
has rolled. `TimeSeriesStore` keeps that window: every `interval_s`
the owner feeds it the current gauge readings plus the raw cumulative
counters, and the store keeps per-window DELTAS of the cumulative ones
(tokens/sec per window, finishes per window, histogram count/sum
increments) in bounded deques — O(capacity x n_series) memory, no
timer thread (the engine samples opportunistically from `step()`, so
an idle engine simply stops producing windows rather than burning a
wakeup).

Three consumers, all read-only:

* ``/timeseriesz`` (serve/api.py + metrics/http.py): the `doc()` JSON
  — timestamps plus one list per series — for dashboards-without-a-
  dashboard (curl + jq).
* ``/statusz``: `sparklines()` renders each series as a fixed-width
  Unicode sparkline so a human tailing statusz sees shape, not just
  the latest number.
* `AnomalyMonitor` dumps: every anomaly record carries the preceding
  N-window retrospective, so a quarantine/drain artifact explains
  itself without a co-located Prometheus.

Clock is injectable (`serve.metrics.now` by default) so tests drive
sampling deterministically and fleet replicas share one time base.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable

from solvingpapers_tpu.serve.metrics import now

__all__ = ["TimeSeriesStore", "sparkline"]

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int | None = None) -> str:
    """Render `values` (Nones skipped for scaling, shown as spaces) as
    a Unicode block sparkline. `width` caps the output by keeping the
    NEWEST `width` points — the rolling-window convention: the right
    edge is "now"."""
    vals = list(values)
    if width is not None and width > 0 and len(vals) > width:
        vals = vals[-width:]
    finite = [v for v in vals if v is not None]
    if not finite:
        return ""
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_BLOCKS[0])
        else:
            idx = int((v - lo) / span * (len(_BLOCKS) - 1))
            out.append(_BLOCKS[min(max(idx, 0), len(_BLOCKS) - 1)])
    return "".join(out)


class TimeSeriesStore:
    """Bounded ring of periodic metric samples with counter deltas.

    `sample(gauges, cumulative)` appends one window: gauge values are
    stored as-is; cumulative values are stored as the DELTA against
    the previous raw reading (the first window's delta is the raw
    value — everything before the store existed counts as window 0;
    a counter that goes backwards, i.e. the owner was swapped out,
    clamps to 0 rather than storing a negative rate). A series that
    appears mid-run back-fills None for the windows it missed; a
    series absent from a sample records None for that window — doc()
    rows always align with the timestamp ring.

    Thread-safe: the owner samples from its step thread while status
    request threads read `doc()`/`sparklines()`.
    """

    def __init__(self, capacity: int = 120, interval_s: float = 1.0,
                 clock: Callable[[], float] = now):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        self.capacity = capacity
        self.interval_s = interval_s
        self.clock = clock
        self._lock = threading.Lock()
        self._t: deque[float] = deque(maxlen=capacity)
        self._series: dict[str, deque] = {}
        self._prev_raw: dict[str, float] = {}
        self._last_sample: float | None = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._t)

    def due(self) -> bool:
        """Has `interval_s` elapsed since the last sample (or has none
        been taken)? The owner's opportunistic-sampling guard — cheap
        enough for a per-step call."""
        last = self._last_sample
        return last is None or (self.clock() - last) >= self.interval_s

    def sample(self, gauges: dict, cumulative: dict | None = None,
               ts: float | None = None) -> None:
        """Append one window. `gauges` stores raw values; `cumulative`
        stores per-window deltas vs the previous raw reading."""
        t = self.clock() if ts is None else ts
        row: dict[str, float | None] = dict(gauges)
        for k, raw in (cumulative or {}).items():
            prev = self._prev_raw.get(k)
            self._prev_raw[k] = raw
            row[k] = raw if prev is None else max(raw - prev, 0.0)
        with self._lock:
            n_before = len(self._t)
            self._t.append(t)
            for k, dq in self._series.items():
                dq.append(row.pop(k, None))
            for k, v in row.items():  # series first seen this window
                dq = deque(maxlen=self.capacity)
                dq.extend([None] * n_before)
                dq.append(v)
                self._series[k] = dq
        self._last_sample = t

    def doc(self) -> dict:
        """JSON-safe view: timestamps + aligned per-series rows (the
        ``/timeseriesz`` body)."""
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "capacity": self.capacity,
                "n": len(self._t),
                "t": [round(t, 6) for t in self._t],
                "series": {k: list(dq)
                           for k, dq in sorted(self._series.items())},
            }

    def sparklines(self, width: int = 60) -> dict[str, str]:
        """One sparkline string per series (the /statusz rendering);
        series with no finite point yet are omitted."""
        with self._lock:
            rows = {k: list(dq) for k, dq in sorted(self._series.items())}
        out = {}
        for k, vals in rows.items():
            s = sparkline(vals, width)
            if s:
                out[k] = s
        return out
