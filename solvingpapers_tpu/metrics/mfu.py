"""MFU accounting (SURVEY.md hard part #5).

flops-per-token uses the PaLM-appendix convention: 6N for the
fwd+bwd matmul flops of N *active* parameters plus the 12·L·D·S
attention-score term. For MoE models pass the active (routed top-k +
shared + non-expert) parameter count, not the total.
"""

from __future__ import annotations

import math
import warnings

import jax

# bf16 peak TFLOP/s per chip by TPU generation (public spec sheets).
_PEAK_TFLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,  # v5p
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}

_warned_kinds: set[str] = set()


def chip_peak_flops(device=None) -> float:
    """bf16 peak FLOP/s for `device`, or NaN when the chip is unknown.

    NaN is a deliberate sentinel: CPU hosts and unrecognized backends
    have no table entry, and the old conservative-default behavior
    (assume v5e) silently mis-scaled every downstream MFU number —
    garbage that looked plausible. NaN instead propagates visibly
    through `mfu()` and lets callers gate (`math.isfinite`) the gauge
    out entirely, which every consumer in this repo now does."""
    device = device or jax.devices()[0]
    kind = str(getattr(device, "device_kind", "") or "").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    # unknown device: warn once per kind so the absent-MFU mystery is
    # self-explaining, then return the sentinel
    if kind not in _warned_kinds:
        _warned_kinds.add(kind)
        warnings.warn(
            f"chip_peak_flops: unrecognized device_kind {kind!r}; "
            "returning NaN — MFU gauges will be omitted rather than "
            "mis-scaled (extend metrics.mfu._PEAK_TFLOPS for new chips)",
            stacklevel=2,
        )
    return float("nan")


def transformer_flops_per_token(
    n_active_params: int, n_layers: int, dim: int, seq_len: int, training: bool = True
) -> float:
    """6N + 12·L·D·S per trained token (2N + 4·L·D·S for inference)."""
    mult = 6 if training else 2
    attn = (12 if training else 4) * n_layers * dim * seq_len
    return mult * n_active_params + attn


def mfu(tokens_per_sec: float, flops_per_token: float, n_chips: int = 1, device=None) -> float:
    """Model FLOP utilization, or NaN when it cannot be computed
    honestly (unknown chip peak, non-finite inputs, zero peak) — NaN
    never raises and never masquerades as a real utilization."""
    peak = chip_peak_flops(device) * n_chips
    achieved = tokens_per_sec * flops_per_token
    if not (math.isfinite(peak) and peak > 0 and math.isfinite(achieved)):
        return float("nan")
    return achieved / peak


def active_param_count(params, top_experts: int | None = None, n_experts: int | None = None) -> int:
    """Parameters touched per token. For MoE pytrees (stacked expert weights
    under .../moe/w1|w2|w3) only top_experts/n_experts of the routed expert
    params count as active — the correct N for the 6N flops model
    (SURVEY.md hard part #5: 'MoE's active-params-only flops')."""
    import jax.tree_util as jtu

    total = 0
    routed = 0
    for path, leaf in jtu.tree_flatten_with_path(params)[0]:
        p = "/".join(str(getattr(k, "key", k)) for k in path)
        total += leaf.size
        if "/moe/w1" in p or "/moe/w2" in p or "/moe/w3" in p:
            routed += leaf.size
    if top_experts and n_experts and routed:
        total -= routed - routed * top_experts // n_experts
    return total
