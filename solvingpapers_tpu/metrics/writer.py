"""Metrics writers.

Replaces the reference's wandb-only logging (deepseekv3/deepseekv3.ipynb
cells 51-54: per-step train_loss / train_perplexity / lr / grad_norm /
tokens; eval val_loss / val_perplexity) with a sink-agnostic interface.
The metric names are kept wandb-compatible so an optional wandb sink can
forward them unchanged; TPU extras (step_time, tokens_per_sec, mfu) ride
the same channel.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import IO, Iterable, Mapping

import numpy as np

from solvingpapers_tpu.metrics.hist import LogHistogram


def percentiles(
    values: Iterable[float], qs: tuple[float, ...] = (50, 95, 99)
) -> dict[str, float]:
    """Summarize observations as ``{"p50": ..., "p95": ..., "p99": ...}``.

    One shared aggregation for every latency-style metric (serve TTFT /
    inter-token latency, step times) so sinks don't hand-roll their own.
    Keys drop a trailing ``.0`` (``p99.9`` stays ``p99.9``). Empty input
    returns ``{}`` — absent beats NaN in a metrics line.
    """
    arr = np.asarray(list(values), dtype=np.float64)
    if arr.size == 0:
        return {}
    out = {}
    for q in qs:
        label = f"p{int(q)}" if float(q).is_integer() else f"p{q}"
        out[label] = float(np.percentile(arr, q))
    return out


class Ring:
    """Bounded ring buffer of scalar observations with percentile summary.

    Long-lived serving loops observe unbounded streams (one latency per
    token); the ring keeps the last `capacity` of them so memory stays
    constant and the summary tracks recent behavior.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._buf = np.empty(capacity, np.float64)
        self._n = 0  # total ever added; min(_n, capacity) are live

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    @property
    def total_added(self) -> int:
        return self._n

    def add(self, value: float) -> None:
        self._buf[self._n % self.capacity] = float(value)
        self._n += 1

    def values(self) -> np.ndarray:
        return self._buf[: len(self)].copy()

    def mean(self) -> float:
        return float(self._buf[: len(self)].mean()) if len(self) else float("nan")

    def percentiles(
        self, qs: tuple[float, ...] = (50, 95, 99)
    ) -> dict[str, float]:
        return percentiles(self._buf[: len(self)], qs)


class MetricsWriter:
    # sinks that can render a `metrics.hist.LogHistogram` value natively
    # set this True; emitters (ServeMetrics.emit) feed everyone else the
    # flat float summary instead, so JSONL/wandb/console never see one
    accepts_histograms = False

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConsoleWriter(MetricsWriter):
    def __init__(self, stream: IO | None = None, every: int = 1):
        # stream resolved at write time so runtime redirection works
        self.stream = stream
        self.every = max(every, 1)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        if step % self.every:
            return
        parts = " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in metrics.items()
        )
        print(f"step {step}: {parts}", file=self.stream or sys.stdout, flush=True)


class JSONLWriter(MetricsWriter):
    """Append-mode JSONL sink; usable as a context manager. `close()`
    flushes AND fsyncs so a crash immediately after (the post-mortem
    case anomaly dumps exist for) cannot lose the tail of the log to a
    kernel page cache that never hit disk."""

    def __init__(self, path: str):
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.f = open(path, "a", buffering=1)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        rec = {"step": step, "time": time.time(), **{k: float(v) for k, v in metrics.items()}}
        self.f.write(json.dumps(rec) + "\n")

    def __enter__(self) -> "JSONLWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def close(self) -> None:
        if self.f.closed:
            return
        self.f.flush()
        os.fsync(self.f.fileno())
        self.f.close()


class PrometheusTextWriter(MetricsWriter):
    """Prometheus node-exporter textfile-collector sink.

    Each `write()` atomically replaces `path` (write to `path + ".tmp"`,
    fsync, `os.replace`) with the CURRENT metric set in text exposition
    format — the contract the textfile collector expects (it must never
    scrape a half-written file, and `os.replace` is atomic on POSIX).
    Metric names are sanitized to the Prometheus grammar
    (``[a-zA-Z_:][a-zA-Z0-9_:]*``): ``serve/ttft_s_p99`` becomes
    ``serve_ttft_s_p99`` and the fractional-percentile key ``p99.9``
    becomes ``p99_9``. The engine `step` rides along as
    ``<prefix>last_step`` so dashboards can detect a stalled exporter.

    No wandb/TensorBoard dependency: point node_exporter's
    ``--collector.textfile.directory`` at the parent directory and the
    serve/train metrics are scrapeable as gauges.

    `metrics.hist.LogHistogram` values render as NATIVE Prometheus
    histograms (``<name>_bucket{le="..."}`` cumulative series + the
    ``_sum``/``_count`` pair) instead of gauges, on this textfile path
    and the live `/metrics` pull path alike (both go through `render`).
    Every bucket edge is emitted even at zero count: PromQL's
    ``sum by (le)`` across replicas needs ALIGNED `le` label sets, and
    the fixed layout is exactly what makes per-replica aggregation
    (`histogram_quantile(0.99, sum by (le) (rate(...)))`) correct.
    """

    accepts_histograms = True

    def __init__(self, path: str, prefix: str = ""):
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.path = path
        self.prefix = self.sanitize(prefix) if prefix else ""

    @staticmethod
    def sanitize(name: str) -> str:
        name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
        if name and name[0].isdigit():
            name = "_" + name
        return name

    @staticmethod
    def _fmt(v: float) -> str:
        # the exposition format spells non-finite values +Inf/-Inf/NaN
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        return repr(float(v))

    @staticmethod
    def _label_str(labels: Mapping[str, object] | None) -> str:
        """``{k="v",...}`` for a constant-label set ("" for none).
        Names are sanitized like metric names; values get the text-
        format escapes (backslash, quote, newline) — a replica id or
        model name with an odd character must not corrupt the scrape."""
        if not labels:
            return ""
        parts = []
        for k, v in labels.items():
            k = PrometheusTextWriter.sanitize(str(k))
            v = (str(v).replace("\\", "\\\\").replace('"', '\\"')
                 .replace("\n", "\\n"))
            parts.append(f'{k}="{v}"')
        return "{" + ",".join(parts) + "}"

    @classmethod
    def render(cls, step: int, metrics: Mapping[str, float],
               prefix: str = "",
               labels: Mapping[str, object] | None = None) -> str:
        """The exposition-format text for one metric set — shared by the
        textfile `write()` path and the live `/metrics` HTTP endpoint
        (metrics/http.py), so names and dedupe rules cannot drift.
        `labels` stamps a constant label set (``replica="r0"``) on every
        rendered series; see `render_sets` for the multi-set contract.

        Dedupes by SANITIZED name (last write wins): two keys that
        collapse to one name ("serve/ttft" vs "serve.ttft") would emit
        the same series twice, and the textfile collector rejects the
        ENTIRE file on a duplicate — one colliding key must not blind
        every dashboard. The `last_step` staleness rider yields to a
        user metric of the same name for the same reason. Histogram
        values claim their ``_bucket``/``_sum``/``_count`` derived names
        ahead of any gauge that would collide with them.
        """
        return cls.render_sets([(step, labels, metrics)], prefix=prefix)

    @classmethod
    def render_sets(cls, sets, prefix: str = "") -> str:
        """One exposition from several ``(step, labels, metrics)`` sets
        — the fleet surface (serve/fleet.py): the merged set carries no
        labels while each replica's set carries ``replica="rN"``, and a
        metric NAME appears once with ONE ``# TYPE`` header over all of
        its labeled series (the text format rejects a name whose series
        are split across groups). Dedupe is by (sanitized name, label
        set) with last write winning — the single-set contract extended
        pointwise; a name that is a histogram in ANY set claims the name
        and its ``_bucket``/``_sum``/``_count`` derivations across ALL
        sets (gauge series under those names are dropped, same
        histogram-wins rule as `render`). Each set gets its own
        ``last_step{labels}`` staleness rider unless it shipped one.
        """
        # name -> {label_str -> formatted value | LogHistogram}; plain
        # dicts keep first-seen name order and last-write series values
        gauges: dict[str, dict[str, str]] = {}
        hists: dict[str, dict[str, LogHistogram]] = {}
        for step, labels, metrics in sets:
            ls = cls._label_str(labels)
            rider = f"{prefix}last_step"
            saw_rider = False
            for k, v in metrics.items():
                name = prefix + cls.sanitize(k)
                saw_rider = saw_rider or name == rider
                if isinstance(v, LogHistogram):
                    hists.setdefault(name, {})[ls] = v
                else:
                    gauges.setdefault(name, {})[ls] = cls._fmt(float(v))
            if not saw_rider:
                # setdefault on the SERIES: the rider must never clobber
                # a user gauge another set already placed at this name +
                # label set, and a later user gauge still overwrites it
                gauges.setdefault(rider, {}).setdefault(
                    ls, str(int(step)))
        reserved = {
            f"{h}{suffix}"
            for h in hists for suffix in ("_bucket", "_sum", "_count")
        }
        for name in (reserved | set(hists)) & set(gauges):
            del gauges[name]  # the histogram's series win the collision
        lines = []
        for name, series in gauges.items():
            lines.append(f"# TYPE {name} gauge")
            for ls, value in series.items():
                lines.append(f"{name}{ls} {value}")
        for name, series in hists.items():
            lines.append(f"# TYPE {name} histogram")
            for ls, h in series.items():
                # ONE cumulative pass feeds both the buckets and _count,
                # so the +Inf bucket == _count invariant (which
                # OpenMetrics parsers and histogram_quantile enforce)
                # holds even when a serving thread records into the live
                # histogram mid-render — a concurrently-added
                # observation is wholly absent from this scrape rather
                # than torn across its series
                cums = h.cumulative_counts()
                base = ls[1:-1] + "," if ls else ""
                for le, cum in zip(h.bucket_bounds(), cums):
                    label = ("+Inf" if le == float("inf")
                             else repr(float(le)))
                    lines.append(
                        f'{name}_bucket{{{base}le="{label}"}} {cum}')
                lines.append(f"{name}_sum{ls} {cls._fmt(h.sum)}")
                lines.append(f"{name}_count{ls} {cums[-1] if cums else 0}")
        return "\n".join(lines) + "\n"

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.render(step, metrics, prefix=self.prefix))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


class TensorBoardWriter(MetricsWriter):
    """TensorBoard scalars via torch.utils.tensorboard (lazy import)."""

    def __init__(self, log_dir: str):
        from torch.utils.tensorboard import SummaryWriter

        self.writer = SummaryWriter(log_dir)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        for k, v in metrics.items():
            self.writer.add_scalar(k, float(v), step)

    def close(self) -> None:
        self.writer.close()


class WandbWriter(MetricsWriter):
    """wandb sink with the reference's metric names (deepseekv3 cell 54).
    Lazy import: raises with guidance if wandb is not installed."""

    def __init__(self, project: str, config: Mapping | None = None, **kwargs):
        try:
            import wandb
        except ImportError as e:  # pragma: no cover
            raise RuntimeError(
                "wandb is not installed; use JSONLWriter/TensorBoardWriter "
                "or `pip install wandb`"
            ) from e
        self.wandb = wandb
        self.run = wandb.init(project=project, config=dict(config or {}), **kwargs)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        self.wandb.log({k: float(v) for k, v in metrics.items()}, step=step)

    def close(self) -> None:
        self.run.finish()


class MultiWriter(MetricsWriter):
    def __init__(self, *writers: MetricsWriter):
        self.writers = writers

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        for w in self.writers:
            w.write(step, metrics)

    def close(self) -> None:
        """Close EVERY writer even when one raises (a dead wandb socket
        must not leave the JSONL tail unflushed); the first error
        propagates after the sweep completes."""
        errs = []
        for w in self.writers:
            try:
                w.close()
            except Exception as e:  # noqa: BLE001 — sweep must finish
                errs.append(e)
        if errs:
            raise errs[0]
