"""Compile & memory observatory: XLA compile registry, HBM ledger,
per-program roofline.

The flight recorder (metrics/trace.py) answers "where did the wall clock
go"; this module answers the device/compiler-side questions production
serving stacks triage capacity and latency regressions with:

* `CompileRegistry` — every jitted program the engines run is routed
  through an ahead-of-time signature cache: a call whose abstract
  signature (static args + dynamic shapes/dtypes) was never seen lowers
  and compiles explicitly (`jit(f).lower(...).compile()`), so the
  registry records the TRUE compile wall time plus the executable's
  `cost_analysis()` flops / bytes-accessed and `memory_analysis()` temp
  bytes — and subsequent calls dispatch the cached executable directly.
  A **recompile storm** (same program, >= `storm_k` new signatures
  inside `storm_window_s`) is the classic silent latency killer (a shape
  that never buckets, a stray weak_type flip); the registry counts it,
  warns once per program, and — when the engine's `AnomalyMonitor` is
  armed — dumps the flight-recorder ring through
  `AnomalyMonitor.observe_recompile`.

  Compiled executables are shared process-wide (`_AOT_CACHE`, the moral
  equivalent of jax's own jit cache) so a warmed benchmark arm or a
  second engine over the same model does not pay compilation twice;
  per-registry stats (calls, run seconds, signature misses) stay local
  so each engine reports its own view.

* `HBMLedger` — named live-byte pools (`params`, `kv_pool`,
  `prefix_cache`, `opt_state`, ...) registered as zero-arg providers and
  read lazily, plus the registry's max per-program temp bytes, give a
  projected decode-step peak; capacity is a PER-CHIP number, so mesh-
  aware providers use `pytree_device_bytes` (shard_shape bytes per
  device) rather than global bytes; against the device capacity
  (`memory_stats()["bytes_limit"]` where the backend reports it, or an
  explicit override) the ledger computes headroom and warns BEFORE the
  projected peak exceeds capacity — the admission-control signal, not
  the OOM post-mortem.

* roofline — joining cost_analysis flops/bytes with the registry's
  measured per-program run seconds yields achieved FLOP/s, arithmetic
  intensity (flops / byte), and per-program MFU against
  `metrics.mfu.chip_peak_flops` (NaN-safe: unknown backends simply omit
  the MFU gauge). The same join is available offline from an exported
  trace via `metrics.trace.summarize_trace` (the registry emits one
  `compile` event per compilation when a recorder is attached).

Everything is opt-in (`ServeConfig.xla_obs` / `TrainConfig.xla_obs`);
with it off the engines never import this module and every hook site is
a single `is not None` branch. With it on, program calls are fenced
(`block_until_ready`) so run seconds are device-true — the same
observability-mode contract as flight-recorder tracing, held to the
same paired-bench overhead budget (`obs_overhead_pct` in
BENCH_serve.json).
"""

from __future__ import annotations

import hashlib
import math
import os
import tempfile
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable

import jax

from solvingpapers_tpu.metrics.mfu import chip_peak_flops
from solvingpapers_tpu.metrics.writer import PrometheusTextWriter


def pytree_bytes(tree) -> int:
    """Total GLOBAL bytes of every array leaf in a pytree (device or
    host) — the logical array sizes, regardless of sharding."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            total += int(size) * int(itemsize)
    return total


def pytree_device_bytes(tree) -> int:
    """PER-DEVICE bytes of a pytree: a sharded leaf occupies its
    `Sharding.shard_shape` bytes on each device, not its global bytes —
    the number HBM capacity accounting must book under a mesh (a
    TP-sharded kernel costs 1/model of its global size per chip; a
    replicated one costs full size everywhere). Host arrays and leaves
    without a sharding fall back to global bytes (single-device
    semantics, where the two coincide)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if itemsize is None:
            continue
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                total += int(
                    math.prod(sharding.shard_shape(leaf.shape)) * itemsize
                )
                continue
            except Exception:  # exotic sharding: global beats a crash
                pass
        size = getattr(leaf, "size", None)
        if size is not None:
            total += int(size) * int(itemsize)
    return total


def device_capacity_bytes(device=None) -> int | None:
    """Device memory capacity, or None where the backend does not report
    it (CPU: `memory_stats()` is None — the ledger then omits headroom
    gauges instead of inventing a number)."""
    device = device or jax.devices()[0]
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is None:
        return None
    try:
        stats = stats_fn()
    except Exception:  # backend quirk: absent beats a crashed gauge read
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


# process-global executable cache: (id(jitted), statics, dynamic avals)
# -> _Executable. `jitted` is kept alive by the entry itself (strong ref)
# so an id() can never be recycled onto a different function while its
# executables are cached.
_AOT_CACHE: dict[tuple, "_Executable"] = {}
_AOT_LOCK = threading.Lock()


def clear_aot_cache() -> None:
    """Drop every cached executable (tests that must observe true
    compiles call this first; production code never needs to)."""
    with _AOT_LOCK:
        _AOT_CACHE.clear()


class _Executable:
    """One compiled program variant + its compile-time analyses."""

    __slots__ = ("compiled", "jitted", "compile_s", "flops",
                 "bytes_accessed", "temp_bytes", "arg_bytes", "out_bytes",
                 "collectives", "anatomy")

    def __init__(self, compiled, jitted, compile_s: float):
        self.compiled = compiled
        self.jitted = jitted  # strong ref: pins id(jitted) while cached
        self.compile_s = compile_s
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.temp_bytes = 0
        self.arg_bytes = 0
        self.out_bytes = 0
        # parse_hlo_collectives result, or None while unparsed (parsing
        # is lazy and gated on CompileRegistry(collectives=True) — the
        # HLO text render is not free, and most registries never ask)
        self.collectives: dict | None = None
        # parse_hlo_costs result (metrics/hlo_cost.py), same lazy
        # contract gated on CompileRegistry(anatomy=True): None = never
        # parsed, {} = parse failed (as_text unavailable) — absence,
        # never an invented zero ledger
        self.anatomy: dict | None = None
        try:
            ca = compiled.cost_analysis()
            d = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
            self.flops = float(d.get("flops", 0.0))
            self.bytes_accessed = float(d.get("bytes accessed", 0.0))
        except Exception:
            pass  # not every backend implements cost_analysis
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                self.temp_bytes = int(ma.temp_size_in_bytes)
                self.arg_bytes = int(ma.argument_size_in_bytes)
                self.out_bytes = int(ma.output_size_in_bytes)
        except Exception:
            pass  # memory_analysis is backend-dependent


class _SigStats:
    """Per-registry stats for one (program, signature) variant."""

    __slots__ = ("exe", "calls", "run_s", "cached")

    def __init__(self, exe: _Executable, cached: bool):
        self.exe = exe
        self.calls = 0
        self.run_s = 0.0
        self.cached = cached  # served from the process-global cache


class _ProgramStats:
    """Per-registry stats for one named program across its signatures."""

    __slots__ = ("name", "signatures", "compile_s", "compiles", "cached",
                 "miss_stamps", "storms", "storm_warned", "in_storm")

    def __init__(self, name: str):
        self.name = name
        self.signatures: dict[Any, _SigStats] = {}
        self.compile_s = 0.0  # true XLA compiles this registry triggered
        self.compiles = 0  # signature misses (new program variants seen)
        self.cached = 0  # misses served by the process-global cache
        self.miss_stamps: deque[float] = deque(maxlen=64)
        self.storms = 0  # storm EPISODES (below-k -> at-k transitions)
        self.storm_warned = False
        self.in_storm = False

    @property
    def calls(self) -> int:
        return sum(s.calls for s in self.signatures.values())

    @property
    def run_s(self) -> float:
        return sum(s.run_s for s in self.signatures.values())

    def weighted_flops(self) -> float:
        return sum(s.exe.flops * s.calls for s in self.signatures.values())

    def weighted_bytes(self) -> float:
        return sum(
            s.exe.bytes_accessed * s.calls for s in self.signatures.values()
        )


class CompileRegistry:
    """Signature-keyed AOT dispatch + compile/roofline accounting.

    `call(program, key, jitted, args, static_argnums)` is the single
    entry point: `key` is a CHEAP hashable the call site derives from
    what actually varies (e.g. the prefill bucket's `(padded, chunk,
    start)`) so the hot path never hashes a parameter pytree; the full
    abstract signature is only computed on a registry-level miss, to key
    the process-global executable cache safely across engines whose
    cheap keys collide (two engines over different models share the same
    module-level jitted function).

    `time_programs=True` (default) fences every dispatch so per-program
    run seconds — the roofline denominator — are device wall time, not
    dispatch time. Observability mode, same contract as tracing.
    """

    def __init__(
        self,
        trace=None,
        monitor=None,
        storm_k: int = 8,
        storm_window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        time_programs: bool = True,
        collectives: bool = False,
        anatomy: bool = False,
        hlo_dir: str | None = None,
    ):
        if storm_k < 2:
            raise ValueError(f"storm_k must be >= 2, got {storm_k}")
        if storm_window_s <= 0:
            raise ValueError(
                f"storm_window_s must be > 0, got {storm_window_s}"
            )
        self.trace = trace  # metrics.trace.FlightRecorder | None
        self.monitor = monitor  # metrics.trace.AnomalyMonitor | None
        self.storm_k = storm_k
        self.storm_window_s = storm_window_s
        self.clock = clock
        self.time_programs = time_programs
        # mesh observatory mode (metrics/mesh_obs.py): parse each
        # compiled program's HLO text for collective ops so the ledger
        # can report per-program comm bytes — compile-time-only cost
        self.collectives = collectives
        # program-anatomy mode (metrics/hlo_cost.py): parse each
        # compiled program's HLO text into the per-op-category cost
        # ledger (gather/scatter/dot/convert/... flops + output-shape
        # bytes, top-k heaviest ops) — compile-time-only cost, same
        # lazy contract as the collective ledger
        self.anatomy = anatomy
        # optional per-signature compiled-HLO text dump directory
        # (ServeConfig.obs_hlo_dir): one file per TRUE compile, written
        # atomically (tmp + rename), named
        # <sanitized program>__<signature hash>.hlo.txt — so anatomy
        # claims can be diffed offline against the exact HLO they came
        # from. Dump failures warn once and never break a compile.
        self.hlo_dir = hlo_dir
        self._hlo_dump_warned = False
        self._programs: dict[str, _ProgramStats] = {}
        self._lock = threading.Lock()
        # chip peak for per-program MFU; NaN on backends without a table
        # entry (metrics/mfu.py) — MFU gauges are omitted, never garbage
        self.peak_flops = chip_peak_flops()

    # ------------------------------------------------------------ dispatch

    def call(self, program: str, key, jitted, args: tuple,
             static_argnums: tuple = ()):
        """Run `jitted(*args)` through the registry: compile-on-new-
        signature (recorded), then dispatch the cached executable with
        the static args stripped (the AOT calling convention)."""
        st = self._programs.get(program)
        if st is None:
            with self._lock:
                st = self._programs.setdefault(program,
                                               _ProgramStats(program))
        sig = st.signatures.get(key)
        if sig is None:
            sig = self._admit(program, st, key, jitted, args, static_argnums)
        if static_argnums:
            dyn = tuple(a for i, a in enumerate(args)
                        if i not in static_argnums)
        else:
            dyn = args
        t0 = self.clock()
        out = sig.exe.compiled(*dyn)
        if self.time_programs:
            out = jax.block_until_ready(out)
            sig.run_s += self.clock() - t0
        sig.calls += 1
        return out

    def _admit(self, program: str, st: _ProgramStats, key, jitted,
               args: tuple, static_argnums: tuple) -> _SigStats:
        """Registry-level signature miss: resolve (or build) the
        executable, record the compilation, check for a storm."""
        statics = tuple(args[i] for i in static_argnums)
        avals = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            for i, a in enumerate(args) if i not in static_argnums
            for leaf in jax.tree_util.tree_leaves(a)
        )
        global_key = (id(jitted), statics, avals)
        with _AOT_LOCK:
            exe = _AOT_CACHE.get(global_key)
        cached = exe is not None
        if exe is None:
            lowered = jitted.lower(*args)
            t0 = self.clock()
            compiled = lowered.compile()
            exe = _Executable(compiled, jitted, self.clock() - t0)
            with _AOT_LOCK:
                exe = _AOT_CACHE.setdefault(global_key, exe)
        # the HLO text render is not free: do it ONCE per executable and
        # feed every consumer (collective ledger, anatomy ledger, dump)
        hlo_text: str | None = None
        if ((self.collectives and exe.collectives is None)
                or (self.anatomy and exe.anatomy is None)
                or (self.hlo_dir is not None and not cached)):
            try:
                hlo_text = exe.compiled.as_text()
            except Exception:  # backend without as_text: absent, not 0s
                hlo_text = None
        if self.collectives and exe.collectives is None:
            # lazy (a cache hit may come from a registry that never
            # parsed); a benign race would just parse twice
            from solvingpapers_tpu.metrics.mesh_obs import (
                parse_hlo_collectives,
            )

            try:
                exe.collectives = (parse_hlo_collectives(hlo_text)
                                   if hlo_text is not None else {})
            except Exception:  # {} = parse failed: absence, never zeros
                exe.collectives = {}
        if self.anatomy and exe.anatomy is None:
            from solvingpapers_tpu.metrics.hlo_cost import parse_hlo_costs

            try:
                exe.anatomy = (parse_hlo_costs(hlo_text)
                               if hlo_text is not None else {})
            except Exception:  # same contract as the collective ledger
                exe.anatomy = {}
        if self.hlo_dir is not None and not cached and hlo_text is not None:
            self._dump_hlo(program, key, hlo_text)
        sig = _SigStats(exe, cached)
        with self._lock:
            st.signatures[key] = sig
            st.compiles += 1
            if cached:
                st.cached += 1
            else:
                st.compile_s += exe.compile_s
            now = self.clock()
            st.miss_stamps.append(now)
            while st.miss_stamps and now - st.miss_stamps[0] > \
                    self.storm_window_s:
                st.miss_stamps.popleft()
            over = len(st.miss_stamps) >= self.storm_k
            # fire once per EPISODE (the below-k -> at-k transition): a
            # sustained storm stays over the threshold for every further
            # miss, and re-dumping per miss would both spam an fsync'd
            # multi-KB record onto the compile path and exhaust the
            # AnomalyMonitor's shared max_dumps budget, silencing later
            # timeout/reject anomalies in the same run
            storm = over and not st.in_storm
            st.in_storm = over
            if storm:
                st.storms += 1
        if self.trace is not None:
            ev = dict(
                program=program, signature=str(key),
                compile_s=round(exe.compile_s, 6), flops=exe.flops,
                bytes=exe.bytes_accessed, temp_bytes=exe.temp_bytes,
                cached=int(cached),
            )
            if math.isfinite(self.peak_flops):
                ev["peak_flops"] = self.peak_flops
            if exe.collectives and exe.collectives.get("ops"):
                # collective ledger (mesh observatory on): the offline
                # trace-summary comm section joins on these
                ev["comm_ops"] = exe.collectives["ops"]
                ev["comm_bytes"] = exe.collectives["bytes"]
                ev["comm_by_type"] = {
                    k: dict(v)
                    for k, v in exe.collectives["by_type"].items()
                }
            if exe.anatomy and exe.anatomy.get("ops"):
                # per-op anatomy ledger: the offline trace-summary
                # anatomy section joins on this one nested arg (empty
                # parse = absent, matching the statusz contract)
                ev["anatomy"] = exe.anatomy
            self.trace.instant("compile", "xla", "xla", **ev)
        if storm:
            if not st.storm_warned:
                st.storm_warned = True
                warnings.warn(
                    f"recompile storm: program {program!r} saw "
                    f"{len(st.miss_stamps)} new signatures within "
                    f"{self.storm_window_s:g}s — shape bucketing is not "
                    "holding, every miss pays a fresh XLA compile",
                    stacklevel=3,
                )
            if self.monitor is not None:
                self.monitor.observe_recompile(
                    program, new_signatures=len(st.miss_stamps),
                    window_s=self.storm_window_s,
                )
        return sig

    def _dump_hlo(self, program: str, key, text: str) -> None:
        """Write one compiled signature's HLO text to `hlo_dir`
        atomically (tmp + rename — a reader or an uploader never sees a
        torn file): ``<sanitized program>__<signature hash>.hlo.txt``.
        Prometheus-style sanitized program names keep the files
        shell/artifact safe; the hash keys the exact signature so two
        prefill buckets never clobber each other."""
        try:
            os.makedirs(self.hlo_dir, exist_ok=True)
            digest = hashlib.sha1(
                repr(key).encode("utf-8", "replace")
            ).hexdigest()[:12]
            name = (f"{PrometheusTextWriter.sanitize(program)}"
                    f"__{digest}.hlo.txt")
            fd, tmp = tempfile.mkstemp(dir=self.hlo_dir,
                                       prefix=".hlo_tmp_")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(text)
                os.replace(tmp, os.path.join(self.hlo_dir, name))
            except BaseException:
                os.unlink(tmp)
                raise
        except OSError as e:
            if not self._hlo_dump_warned:
                self._hlo_dump_warned = True
                warnings.warn(
                    f"obs_hlo_dir: cannot dump compiled HLO to "
                    f"{self.hlo_dir!r} ({e}) — continuing without dumps",
                    stacklevel=3,
                )

    # ------------------------------------------------------------- reading

    def anatomy_stats(self) -> dict:
        """Per-program anatomy ledger (programs whose registry was built
        with ``anatomy=True`` and that parsed): {program:
        parse_hlo_costs result} from the heaviest-bytes signature (the
        steady-state variant — the collective_stats convention). A
        program built without the flag, or whose as_text failed, is
        simply absent — never a zero ledger."""
        from solvingpapers_tpu.metrics.hlo_cost import best_anatomy

        with self._lock:
            out = {}
            for name, st in self._programs.items():
                best = best_anatomy(
                    s.exe.anatomy for s in st.signatures.values()
                )
                if best is not None:
                    out[name] = best
        return out

    def collective_stats(self) -> dict:
        """Per-program collective ledger (programs whose registry was
        built with `collectives=True` and that parsed): {program:
        {"ops", "bytes", "by_type", "calls", "run_s"}} — ops/bytes from
        the largest-traffic signature (the steady-state variant, the
        flops_per_call convention), calls/run_s summed for the wall
        join. A compiled program with no collectives reports a true
        zero; an unparsed one (registry built without the flag) is
        simply absent."""
        with self._lock:
            out = {}
            for name, st in self._programs.items():
                best: dict | None = None
                for s in st.signatures.values():
                    c = s.exe.collectives
                    # None = never parsed; {} = parse FAILED (as_text
                    # unavailable) — both are absence, never a zero. A
                    # parsed zero-collective program carries the full
                    # {"ops": 0, "bytes": 0, "by_type": {}} structure.
                    if not c:
                        continue
                    if best is None or c.get("bytes", 0) > best.get(
                            "bytes", 0):
                        best = c
                if best is None:
                    continue
                out[name] = {
                    "ops": best.get("ops", 0),
                    "bytes": best.get("bytes", 0),
                    "by_type": {k: dict(v)
                                for k, v in best.get("by_type", {}).items()},
                    "calls": st.calls,
                    "run_s": st.run_s,
                }
        return out

    def max_temp_bytes(self) -> int:
        """Largest per-program XLA temp allocation seen — the scratch the
        ledger adds on top of live pools for the projected peak."""
        with self._lock:
            return max(
                (s.exe.temp_bytes
                 for st in self._programs.values()
                 for s in st.signatures.values()),
                default=0,
            )

    @property
    def total_compile_s(self) -> float:
        with self._lock:
            return sum(st.compile_s for st in self._programs.values())

    def gauges(self) -> dict[str, float]:
        """Flat `compile/*` + `roofline/*` metric keys (ServeMetrics
        gauge-provider / train log-row shape). The whole read holds the
        registry lock: gauge requests arrive from the status server's
        threads while the engine thread may be inserting a new signature
        (`_admit`), and iterating the signatures dict during that insert
        would raise mid-scrape."""
        with self._lock:
            progs = list(self._programs.values())
            out = {
                "compile/programs": float(len(progs)),
                "compile/compilations": float(
                    sum(p.compiles for p in progs)
                ),
                "compile/cached": float(sum(p.cached for p in progs)),
                "compile/recompiles": float(
                    sum(max(p.compiles - 1, 0) for p in progs)
                ),
                "compile/storms": float(sum(p.storms for p in progs)),
                "compile/time_s": float(sum(p.compile_s for p in progs)),
            }
            for p in progs:
                run_s = p.run_s
                if run_s <= 0.0 or not p.calls:
                    continue
                name = PrometheusTextWriter.sanitize(p.name)
                flops = p.weighted_flops()
                nbytes = p.weighted_bytes()
                achieved = flops / run_s
                out[f"roofline/{name}_flops_per_s"] = achieved
                if nbytes > 0:
                    out[f"roofline/{name}_intensity"] = flops / nbytes
                if math.isfinite(self.peak_flops) and self.peak_flops > 0 \
                        and flops > 0:
                    out[f"roofline/{name}_mfu"] = achieved / self.peak_flops
        return out

    def snapshot(self) -> dict:
        """Structured view for /statusz: per-program signature counts,
        compile seconds, calls, run seconds, and the roofline join.
        Built entirely under the lock — see `gauges`."""
        with self._lock:
            progs = {
                name: {
                    "signatures": len(st.signatures),
                    "compilations": st.compiles,
                    "cached": st.cached,
                    "compile_time_s": round(st.compile_s, 6),
                    "calls": st.calls,
                    "run_time_s": round(st.run_s, 6),
                    "storms": st.storms,
                    "flops_per_call": max(
                        (s.exe.flops for s in st.signatures.values()),
                        default=0.0,
                    ),
                    "bytes_per_call": max(
                        (s.exe.bytes_accessed
                         for s in st.signatures.values()),
                        default=0.0,
                    ),
                    "temp_bytes": max(
                        (s.exe.temp_bytes for s in st.signatures.values()),
                        default=0,
                    ),
                    "_flops": st.weighted_flops(),
                    "_bytes": st.weighted_bytes(),
                    # -1 = no signature parsed (collectives off, or the
                    # parse failed — empty dict): the key is dropped
                    # below rather than faked as zero
                    "_comm": max(
                        (s.exe.collectives.get("bytes", 0)
                         if s.exe.collectives else -1
                         for s in st.signatures.values()),
                        default=-1,
                    ),
                }
                for name, st in self._programs.items()
            }
            # per-program anatomy (ledger of the heaviest-bytes parsed
            # signature — hlo_cost.best_anatomy, ONE pick convention
            # with anatomy_stats and the offline trace join): present
            # IFF the registry parses anatomy and as_text worked — the
            # statusz `programs.<name>.anatomy` surface the trace
            # section and README document
            from solvingpapers_tpu.metrics.hlo_cost import best_anatomy

            for name, st in self._programs.items():
                best = best_anatomy(
                    s.exe.anatomy for s in st.signatures.values()
                )
                if best is not None:
                    progs[name]["anatomy"] = best
        for d in progs.values():
            comm = d.pop("_comm")
            if comm >= 0:
                d["comm_bytes_per_call"] = comm
            flops, nbytes = d.pop("_flops"), d.pop("_bytes")
            if d["run_time_s"] > 0 and d["calls"]:
                d["achieved_flops_per_s"] = flops / d["run_time_s"]
                if nbytes > 0:
                    d["intensity_flops_per_byte"] = flops / nbytes
                if math.isfinite(self.peak_flops) and flops > 0:
                    d["mfu"] = d["achieved_flops_per_s"] / self.peak_flops
        return {
            "programs": progs,
            "total_compile_time_s": round(
                sum(d["compile_time_s"] for d in progs.values()), 6
            ),
            "storms": sum(d["storms"] for d in progs.values()),
        }


class HBMLedger:
    """Named live-byte pools + projected-peak headroom accounting.

    `register(name, provider)` attaches a zero-arg callable returning
    the pool's CURRENT device bytes (providers read live engine state,
    so gauges are always fresh and the ledger never caches stale
    sizes); `temp_fn` (typically `CompileRegistry.max_temp_bytes`) adds
    the largest per-program scratch on top for the projected peak.
    `check()` warns once when the projection exceeds the device
    capacity — call it where memory can grow (the engine does so per
    admission), not per token.
    """

    def __init__(self, capacity_bytes: int | None = None, device=None):
        self.pools: dict[str, Callable[[], int]] = {}
        self.temp_fn: Callable[[], int] | None = None
        self.capacity_bytes = (
            capacity_bytes if capacity_bytes is not None
            else device_capacity_bytes(device)
        )
        self._warned = False

    def register(self, name: str, provider: Callable[[], int] | int) -> None:
        if not callable(provider):
            value = int(provider)
            provider = lambda: value  # noqa: E731 — constant pool size
        if name in self.pools:
            raise ValueError(f"pool {name!r} already registered")
        self.pools[name] = provider

    def pool_bytes(self) -> dict[str, int]:
        return {name: int(fn()) for name, fn in self.pools.items()}

    def live_bytes(self) -> int:
        return sum(self.pool_bytes().values())

    def temp_bytes(self) -> int:
        return int(self.temp_fn()) if self.temp_fn is not None else 0

    def projected_peak_bytes(self) -> int:
        """Live pools + the largest per-program XLA scratch: the
        estimate of the next decode step's high-water mark."""
        return self.live_bytes() + self.temp_bytes()

    def headroom_bytes(self) -> int | None:
        if self.capacity_bytes is None:
            return None
        return self.capacity_bytes - self.projected_peak_bytes()

    def check(self) -> bool:
        """True (and a one-shot warning) when the projected peak exceeds
        capacity — the moment admission control should stop admitting."""
        if self.capacity_bytes is None:
            return False
        peak = self.projected_peak_bytes()
        if peak <= self.capacity_bytes:
            return False
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"projected HBM peak {peak} bytes exceeds device capacity "
                f"{self.capacity_bytes} bytes (pools {self.pool_bytes()}, "
                f"program temp {self.temp_bytes()}) — the next step may "
                "OOM; shed load or shrink the pools",
                stacklevel=2,
            )
        return True

    def gauges(self) -> dict[str, float]:
        """Flat `mem/*` metric keys."""
        pools = self.pool_bytes()
        out = {f"mem/{PrometheusTextWriter.sanitize(k)}_bytes": float(v)
               for k, v in pools.items()}
        temp = self.temp_bytes()
        live = sum(pools.values())
        out["mem/live_bytes"] = float(live)
        out["mem/program_temp_bytes"] = float(temp)
        out["mem/projected_peak_bytes"] = float(live + temp)
        if self.capacity_bytes is not None:
            out["mem/capacity_bytes"] = float(self.capacity_bytes)
            out["mem/headroom_bytes"] = float(
                self.capacity_bytes - live - temp
            )
        return out

    def snapshot(self) -> dict:
        """Structured view for /statusz."""
        pools = self.pool_bytes()
        temp = self.temp_bytes()
        live = sum(pools.values())
        return {
            "pools": pools,
            "live_bytes": live,
            "program_temp_bytes": temp,
            "projected_peak_bytes": live + temp,
            "capacity_bytes": self.capacity_bytes,
            "headroom_bytes": (
                None if self.capacity_bytes is None
                else self.capacity_bytes - live - temp
            ),
        }
