"""Metrics and observability (L7)."""

from solvingpapers_tpu.metrics.hist import LogHistogram
from solvingpapers_tpu.metrics.writer import (
    MetricsWriter,
    ConsoleWriter,
    JSONLWriter,
    MultiWriter,
    PrometheusTextWriter,
    Ring,
    TensorBoardWriter,
    WandbWriter,
    percentiles,
)
from solvingpapers_tpu.metrics.trace import (
    AnomalyMonitor,
    FlightRecorder,
    TraceEvent,
    format_mesh,
    format_summary,
    summarize_trace,
)
from solvingpapers_tpu.metrics.mfu import (
    transformer_flops_per_token,
    chip_peak_flops,
    mfu,
    active_param_count,
)
from solvingpapers_tpu.metrics.hlo_cost import (
    format_anatomy,
    parse_hlo_costs,
)
from solvingpapers_tpu.metrics.xla_obs import (
    CompileRegistry,
    HBMLedger,
    device_capacity_bytes,
    pytree_bytes,
    pytree_device_bytes,
)
from solvingpapers_tpu.metrics.mesh_obs import (
    MeshObservatory,
    PipelineScheduleInfo,
    bubble_report,
    link_bandwidth_bytes_per_s,
    parse_hlo_collectives,
    probe_stage_costs,
)
from solvingpapers_tpu.metrics.http import StatusServer
