"""solvingpapers_tpu — a TPU-native (JAX/Flax/optax/pjit/Pallas) framework
with the capabilities of the `prashantpandeygit/solvingpapers` reference
collection (GPT, LLaMA3, Gemma, DeepSeekV3 MLA+MoE+MTP, ViT, AlexNet,
autoencoder/VAE, knowledge distillation, attention primitives), rebuilt as
one shared framework: a single ops library, one training engine, jitted
cached inference, and mesh/sharding parallelism over TPU ICI/DCN.

Layout (see SURVEY.md §7):
    ops/        shared primitives: norms, RoPE, activations, attention, losses, sampling
    kernels/    Pallas TPU kernels + pure-jnp references
    sharding/   mesh construction, partition rules, collective wrappers
    models/     Flax model zoo
    data/       tokenizers + dataset/batch pipelines
    train/      the single training engine
    infer/      jitted prefill/decode with KV caches
    serve/      continuous-batching engine: slot pool, FIFO scheduler, mixed step, radix prefix cache
    checkpoint/ Orbax checkpoint manager + params-only export
    metrics/    console/JSONL metrics writers, MFU accounting
    configs/    typed run configs for every workload
"""

__version__ = "0.1.0"

_SERVE_API = ("ServeEngine", "ServeConfig", "KVSlotPool", "FIFOScheduler",
              "Request", "ServeMetrics", "PrefixCache", "PrefixMatch",
              "SamplingParams", "ApiServer", "EngineLoop", "JsonStepper",
              "serve_api")


def __getattr__(name):
    # serve API re-exported lazily (PEP 562): `solvingpapers_tpu.ServeEngine`
    # works without `import solvingpapers_tpu` dragging in jax/flax for
    # consumers that only want metadata
    if name in _SERVE_API:
        from solvingpapers_tpu import serve

        return getattr(serve, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
