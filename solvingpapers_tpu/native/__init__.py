"""Native (C++) runtime hot paths, bound via ctypes.

The compute path of the framework is JAX/XLA/Pallas; this package covers
the host-side runtime around it — tokenization and the batch gather that
feeds the device — as compiled code, the way the reference relies on HF's
native tokenizers (deepseekv3.ipynb cell 6) and pinned DataLoader workers
(cells 12-14).

The shared library is built on demand from `_src/native.cpp` with g++
(no pybind11 in this environment; plain C ABI + ctypes). Every consumer
has a pure-Python fallback, so `available() == False` (no compiler, build
failure) degrades gracefully and is exercised in CI via
SOLVINGPAPERS_TPU_NO_NATIVE=1.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "_src", "native.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "_src", "_native.so")
_lock = threading.Lock()
_lib = None
_load_error: str | None = None

_DTYPE_CODES = {
    np.dtype(np.uint16): 0,
    np.dtype(np.uint32): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int64): 4,
}


def _build() -> str:
    """Compile _src/native.cpp -> _native.so if missing or stale."""
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
        return _LIB_PATH
    tmp = _LIB_PATH + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-o", tmp, _SRC,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, _LIB_PATH)  # atomic under concurrent builders
    return _LIB_PATH


def _load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    with _lock:
        if _lib is not None or _load_error is not None:
            return _lib
        if os.environ.get("SOLVINGPAPERS_TPU_NO_NATIVE"):
            _load_error = "disabled via SOLVINGPAPERS_TPU_NO_NATIVE"
            return None
        try:
            lib = ctypes.CDLL(_build())
        except (OSError, subprocess.CalledProcessError) as e:
            _load_error = (
                e.stderr if isinstance(e, subprocess.CalledProcessError) else str(e)
            )
            return None
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.bpe_ctx_new.restype = ctypes.c_void_p
        lib.bpe_ctx_new.argtypes = [i32p, i32p, i32p, i32p, ctypes.c_int64]
        lib.bpe_ctx_free.argtypes = [ctypes.c_void_p]
        lib.bpe_encode.restype = ctypes.c_int64
        lib.bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), i64p,
            ctypes.c_int64, i32p, ctypes.c_int64, i32p, ctypes.c_int32,
        ]
        lib.bpe_train.restype = ctypes.c_int64
        lib.bpe_train.argtypes = [
            i32p, i64p, i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, i32p, i32p,
        ]
        lib.gather_windows.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, i64p, ctypes.c_int64,
            ctypes.c_int64, i32p, i32p, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library is (or can be) loaded."""
    return _load() is not None


def load_error() -> str | None:
    """Why the native library is unavailable (None if it loaded)."""
    _load()
    return _load_error


def _as_i32(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int32)


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


class NativeBpeEncoder:
    """Merge-loop encoder over a fixed merge table (ids, not strings).

    byte_to_id: (256,) initial symbol id per byte; merges: (n, 3) array of
    (left_id, right_id, merged_id) in rank order.
    """

    def __init__(self, byte_to_id, merges):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_load_error}")
        self._lib = lib
        merges = np.asarray(merges, dtype=np.int32).reshape(-1, 3)
        b2i = _as_i32(byte_to_id)
        if b2i.shape != (256,):
            raise ValueError("byte_to_id must have shape (256,)")
        lefts = np.ascontiguousarray(merges[:, 0])
        rights = np.ascontiguousarray(merges[:, 1])
        merged = np.ascontiguousarray(merges[:, 2])
        self._ctx = lib.bpe_ctx_new(
            _ptr(b2i, ctypes.c_int32), _ptr(lefts, ctypes.c_int32),
            _ptr(rights, ctypes.c_int32), _ptr(merged, ctypes.c_int32),
            len(merges),
        )
        self._chunk_cache: dict[str, np.ndarray] = {}
        self._cache_limit = 1_000_000

    def __del__(self):
        ctx = getattr(self, "_ctx", None)
        if ctx:
            self._lib.bpe_ctx_free(ctx)
            self._ctx = None

    def encode_chunks(self, data: bytes, offsets: np.ndarray,
                      n_threads: int | None = None,
                      counts_out: np.ndarray | None = None) -> np.ndarray:
        """Encode chunks data[offsets[i]:offsets[i+1]] -> flat int32 ids.
        If counts_out (int32, n_chunks) is given it receives per-chunk
        token counts."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        n_chunks = len(offsets) - 1
        if n_chunks <= 0:
            return np.empty(0, np.int32)
        if n_threads is None:
            n_threads = min(os.cpu_count() or 1, 16)
        buf = np.frombuffer(data, dtype=np.uint8)
        cap = max(int(offsets[-1]), 16)
        out = np.empty(cap, np.int32)
        counts_ptr = (
            _ptr(counts_out, ctypes.c_int32) if counts_out is not None else None
        )
        n = self._lib.bpe_encode(
            self._ctx, _ptr(buf, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
            n_chunks, _ptr(out, ctypes.c_int32), cap, counts_ptr, n_threads,
        )
        if n < 0:  # pragma: no cover - cap == total bytes always suffices
            out = np.empty(-n, np.int32)
            n = self._lib.bpe_encode(
                self._ctx, _ptr(buf, ctypes.c_uint8),
                _ptr(offsets, ctypes.c_int64), n_chunks,
                _ptr(out, ctypes.c_int32), -n, counts_ptr, n_threads,
            )
        return out[:n].copy()

    def encode_texts(self, chunks: list[str]) -> np.ndarray:
        """Encode pre-split text chunks with per-unique-chunk caching (the
        native analogue of ByteBPETokenizer._bpe's memo): only novel chunks
        hit the C++ merge loop; repeats are concatenated from the cache."""
        cache = self._chunk_cache
        novel = [c for c in dict.fromkeys(chunks) if c not in cache]
        if novel:
            raw = [c.encode("utf-8") for c in novel]
            offsets = np.zeros(len(raw) + 1, np.int64)
            np.cumsum([len(r) for r in raw], out=offsets[1:])
            counts = np.empty(len(raw), np.int32)
            flat = self.encode_chunks(b"".join(raw), offsets, counts_out=counts)
            bounds = np.zeros(len(raw) + 1, np.int64)
            np.cumsum(counts, out=bounds[1:])
            for i, c in enumerate(novel):
                cache[c] = flat[bounds[i] : bounds[i + 1]]
        if not chunks:
            return np.empty(0, np.int32)
        # Resolve before any eviction: this call may reference chunks cached
        # by earlier calls, which the growth guard below is free to drop.
        out = np.concatenate([cache[c] for c in chunks])
        if len(cache) > self._cache_limit:  # unbounded growth guard
            cache.clear()
            for i, c in enumerate(novel):
                cache[c] = flat[bounds[i] : bounds[i + 1]]
        return out


def bpe_train_native(
    words_flat, offsets, freqs, n_merges_target: int, min_pair_count: int = 2
) -> np.ndarray:
    """Run the incremental BPE trainer; returns (n, 2) (left_id, right_id)
    merges in rank order, merged ids being 256+rank."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    words_flat = _as_i32(words_flat)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    freqs = np.ascontiguousarray(freqs, dtype=np.int64)
    n_words = len(offsets) - 1
    out_l = np.empty(max(n_merges_target, 1), np.int32)
    out_r = np.empty(max(n_merges_target, 1), np.int32)
    n = lib.bpe_train(
        _ptr(words_flat, ctypes.c_int32), _ptr(offsets, ctypes.c_int64),
        _ptr(freqs, ctypes.c_int64), n_words, n_merges_target,
        min_pair_count, _ptr(out_l, ctypes.c_int32),
        _ptr(out_r, ctypes.c_int32),
    )
    return np.stack([out_l[:n], out_r[:n]], axis=1)


def gather_windows_native(
    tokens: np.ndarray, starts: np.ndarray, block_size: int,
    x_out: np.ndarray | None = None, y_out: np.ndarray | None = None,
    n_threads: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(x, y) int32 windows of `tokens` at `starts` — the native equivalent
    of the memmap branch in data.batches.lm_batch_iterator."""
    lib = _load()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_load_error}")
    code = _DTYPE_CODES.get(np.dtype(tokens.dtype))
    if code is None:
        raise ValueError(f"unsupported token dtype {tokens.dtype}")
    if not tokens.flags["C_CONTIGUOUS"]:
        raise ValueError(
            "gather_windows_native needs a C-contiguous token array "
            "(a strided view's base pointer would be misread)"
        )
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    batch = len(starts)
    if x_out is None:
        x_out = np.empty((batch, block_size), np.int32)
    if y_out is None:
        y_out = np.empty((batch, block_size), np.int32)
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 8)
    lib.gather_windows(
        ctypes.c_void_p(tokens.ctypes.data), code,
        _ptr(starts, ctypes.c_int64), batch, block_size,
        _ptr(x_out, ctypes.c_int32), _ptr(y_out, ctypes.c_int32), n_threads,
    )
    return x_out, y_out
