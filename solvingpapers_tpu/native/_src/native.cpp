// Native runtime hot paths: byte-level BPE (encode + trainer) and the
// token-window batch gather that feeds the device.
//
// Capability target: the reference's tokenize-once-then-train pipeline
// (deepseekv3/deepseekv3.ipynb cells 6-14) runs its BPE through HF's native
// tokenizers; the Python fallback in ../data/bpe.py gives semantics, this
// file gives it framework-grade speed. Exposed as a plain C ABI for ctypes
// (no pybind11 in this environment).
//
// Parity contract (tested in tests/test_native.py):
//   * bpe_encode == ByteBPETokenizer.encode's merge loop, chunk by chunk
//   * bpe_train  == ByteBPETokenizer.train under the canonical tie-break
//     (max count, then smallest (left_id, right_id))
//   * gather_windows == the numpy stack/astype in data/batches.py

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

struct BpeCtx {
  // pair -> (rank, merged id); rank = index into the merges list
  std::unordered_map<uint64_t, std::pair<int32_t, int32_t>> pairs;
  int32_t byte_to_id[256];
};

// Apply the classic greedy merge loop to one chunk (lowest-rank adjacent
// pair first, all its occurrences left-to-right per round) — the same loop
// as ByteBPETokenizer._bpe.
void encode_chunk(const BpeCtx& ctx, const uint8_t* bytes, int64_t len,
                  std::vector<int32_t>& out) {
  std::vector<int32_t> word(len);
  for (int64_t i = 0; i < len; ++i) word[i] = ctx.byte_to_id[bytes[i]];
  while (word.size() > 1) {
    int32_t best_rank = INT32_MAX;
    int32_t best_merged = -1;
    uint64_t best_key = 0;
    for (size_t i = 0; i + 1 < word.size(); ++i) {
      uint64_t k = pair_key(word[i], word[i + 1]);
      auto it = ctx.pairs.find(k);
      if (it != ctx.pairs.end() && it->second.first < best_rank) {
        best_rank = it->second.first;
        best_merged = it->second.second;
        best_key = k;
      }
    }
    if (best_merged < 0) break;
    std::vector<int32_t> next;
    next.reserve(word.size());
    for (size_t i = 0; i < word.size();) {
      if (i + 1 < word.size() && pair_key(word[i], word[i + 1]) == best_key) {
        next.push_back(best_merged);
        i += 2;
      } else {
        next.push_back(word[i]);
        i += 1;
      }
    }
    word.swap(next);
  }
  out.insert(out.end(), word.begin(), word.end());
}

}  // namespace

extern "C" {

void* bpe_ctx_new(const int32_t* byte_to_id, const int32_t* lefts,
                  const int32_t* rights, const int32_t* merged,
                  int64_t n_merges) {
  auto* ctx = new BpeCtx();
  std::memcpy(ctx->byte_to_id, byte_to_id, 256 * sizeof(int32_t));
  ctx->pairs.reserve(static_cast<size_t>(n_merges) * 2);
  for (int64_t r = 0; r < n_merges; ++r) {
    ctx->pairs.emplace(pair_key(lefts[r], rights[r]),
                       std::make_pair(static_cast<int32_t>(r), merged[r]));
  }
  return ctx;
}

void bpe_ctx_free(void* ctx) { delete static_cast<BpeCtx*>(ctx); }

// Encode n_chunks byte slices (bytes[offsets[i]:offsets[i+1]]) to token ids.
// out_counts (optional, length n_chunks) receives the per-chunk token count
// so callers can cache per-chunk results. Returns total ids written, or
// -(needed) if out_cap is too small (caller retries with a bigger buffer;
// ids are not partially valid in that case).
int64_t bpe_encode(void* vctx, const uint8_t* bytes, const int64_t* offsets,
                   int64_t n_chunks, int32_t* out, int64_t out_cap,
                   int32_t* out_counts, int32_t n_threads) {
  const auto& ctx = *static_cast<BpeCtx*>(vctx);
  if (n_threads < 1) n_threads = 1;
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(n_chunks, 1));
  std::vector<std::vector<int32_t>> parts(n_threads);
  std::vector<std::thread> threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t]() {
      int64_t lo = n_chunks * t / n_threads;
      int64_t hi = n_chunks * (t + 1) / n_threads;
      auto& part = parts[t];
      part.reserve((offsets[hi] - offsets[lo]) / 2 + 8);
      for (int64_t c = lo; c < hi; ++c) {
        size_t before = part.size();
        encode_chunk(ctx, bytes + offsets[c], offsets[c + 1] - offsets[c],
                     part);
        if (out_counts)
          out_counts[c] = static_cast<int32_t>(part.size() - before);
      }
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (const auto& p : parts) total += static_cast<int64_t>(p.size());
  if (total > out_cap) return -total;
  int64_t pos = 0;
  for (const auto& p : parts) {
    std::memcpy(out + pos, p.data(), p.size() * sizeof(int32_t));
    pos += static_cast<int64_t>(p.size());
  }
  return total;
}

// BPE trainer over pre-split words (id sequences + frequencies). Merge i
// creates symbol id 256+i (the Python trainer's id assignment). Best pair
// per round: max count, tie-break smallest (left, right) — incremental
// counts with a lazy max-heap, so cost scales with words *touched* per
// merge, not corpus size x vocab size like the Python fallback.
// Returns the number of merges produced (<= n_merges_target).
int64_t bpe_train(const int32_t* words_flat, const int64_t* offsets,
                  const int64_t* freqs, int64_t n_words,
                  int64_t n_merges_target, int64_t min_pair_count,
                  int32_t* out_lefts, int32_t* out_rights) {
  std::vector<std::vector<int32_t>> words(n_words);
  for (int64_t w = 0; w < n_words; ++w) {
    words[w].assign(words_flat + offsets[w], words_flat + offsets[w + 1]);
  }
  std::unordered_map<uint64_t, int64_t> count;
  std::unordered_map<uint64_t, std::vector<int64_t>> where;  // may hold stales
  for (int64_t w = 0; w < n_words; ++w) {
    const auto& word = words[w];
    for (size_t i = 0; i + 1 < word.size(); ++i) {
      uint64_t k = pair_key(word[i], word[i + 1]);
      count[k] += freqs[w];
      auto& lst = where[k];
      if (lst.empty() || lst.back() != w) lst.push_back(w);
    }
  }
  // max-heap entries (count, ~left, ~right, key); stale entries are skipped
  // when their recorded count no longer matches the live count.
  using Entry = std::tuple<int64_t, int32_t, int32_t, uint64_t>;
  std::priority_queue<Entry> heap;
  for (const auto& [k, c] : count) {
    heap.emplace(c, ~static_cast<int32_t>(k >> 32),
                 ~static_cast<int32_t>(k & 0xffffffff), k);
  }
  int64_t n_merges = 0;
  while (n_merges < n_merges_target && !heap.empty()) {
    auto [c, nl, nr, k] = heap.top();
    heap.pop();
    auto it = count.find(k);
    if (it == count.end() || it->second != c) continue;  // stale
    if (c < min_pair_count) break;
    const int32_t left = ~nl, right = ~nr;
    const int32_t merged = static_cast<int32_t>(256 + n_merges);
    out_lefts[n_merges] = left;
    out_rights[n_merges] = right;
    ++n_merges;
    count.erase(it);
    auto wh = where.find(k);
    if (wh == where.end()) continue;
    std::vector<int64_t> touched = std::move(wh->second);
    where.erase(wh);
    std::sort(touched.begin(), touched.end());
    touched.erase(std::unique(touched.begin(), touched.end()), touched.end());
    for (int64_t w : touched) {
      auto& word = words[w];
      bool contains = false;
      for (size_t i = 0; i + 1 < word.size(); ++i) {
        if (word[i] == left && word[i + 1] == right) { contains = true; break; }
      }
      if (!contains) continue;  // stale index entry
      const int64_t f = freqs[w];
      auto bump = [&](uint64_t pk, int64_t delta) {
        if (pk == k) return;  // the merged pair itself is being retired
        int64_t& cc = count[pk];
        cc += delta;
        if (cc <= 0) {
          count.erase(pk);
        } else {
          heap.emplace(cc, ~static_cast<int32_t>(pk >> 32),
                       ~static_cast<int32_t>(pk & 0xffffffff), pk);
        }
      };
      for (size_t i = 0; i + 1 < word.size(); ++i) {
        bump(pair_key(word[i], word[i + 1]), -f);
      }
      std::vector<int32_t> next;
      next.reserve(word.size());
      for (size_t i = 0; i < word.size();) {
        if (i + 1 < word.size() && word[i] == left && word[i + 1] == right) {
          next.push_back(merged);
          i += 2;
        } else {
          next.push_back(word[i]);
          i += 1;
        }
      }
      word.swap(next);
      for (size_t i = 0; i + 1 < word.size(); ++i) {
        uint64_t pk = pair_key(word[i], word[i + 1]);
        bump(pk, f);
        auto& lst = where[pk];
        if (lst.empty() || lst.back() != w) lst.push_back(w);
      }
    }
  }
  return n_merges;
}

// Gather batch windows x=data[s:s+block], y=data[s+1:s+block+1] as int32,
// parallel over rows. dtype_code: 0=uint16, 1=uint32, 2=int32, 3=uint8,
// 4=int64. Runs with the GIL released (ctypes), so a Python-side prefetch
// thread overlaps this with the device step.
void gather_windows(const void* data, int32_t dtype_code,
                    const int64_t* starts, int64_t batch, int64_t block,
                    int32_t* x_out, int32_t* y_out, int32_t n_threads) {
  auto copy_row = [&](int64_t r) {
    const int64_t s = starts[r];
    int32_t* x = x_out + r * block;
    int32_t* y = y_out + r * block;
    switch (dtype_code) {
      case 0: {
        const auto* d = static_cast<const uint16_t*>(data) + s;
        for (int64_t i = 0; i < block; ++i) x[i] = d[i];
        for (int64_t i = 0; i < block; ++i) y[i] = d[i + 1];
        break;
      }
      case 1: {
        const auto* d = static_cast<const uint32_t*>(data) + s;
        for (int64_t i = 0; i < block; ++i) x[i] = static_cast<int32_t>(d[i]);
        for (int64_t i = 0; i < block; ++i)
          y[i] = static_cast<int32_t>(d[i + 1]);
        break;
      }
      case 2: {
        const auto* d = static_cast<const int32_t*>(data) + s;
        std::memcpy(x, d, block * sizeof(int32_t));
        std::memcpy(y, d + 1, block * sizeof(int32_t));
        break;
      }
      case 3: {
        const auto* d = static_cast<const uint8_t*>(data) + s;
        for (int64_t i = 0; i < block; ++i) x[i] = d[i];
        for (int64_t i = 0; i < block; ++i) y[i] = d[i + 1];
        break;
      }
      case 4: {
        const auto* d = static_cast<const int64_t*>(data) + s;
        for (int64_t i = 0; i < block; ++i) x[i] = static_cast<int32_t>(d[i]);
        for (int64_t i = 0; i < block; ++i)
          y[i] = static_cast<int32_t>(d[i + 1]);
        break;
      }
    }
  };
  if (n_threads < 1) n_threads = 1;
  n_threads = std::min<int64_t>(n_threads, std::max<int64_t>(batch, 1));
  if (n_threads == 1 || batch < 8) {
    for (int64_t r = 0; r < batch; ++r) copy_row(r);
    return;
  }
  std::vector<std::thread> threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    threads.emplace_back([&, t]() {
      for (int64_t r = batch * t / n_threads; r < batch * (t + 1) / n_threads;
           ++r)
        copy_row(r);
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
