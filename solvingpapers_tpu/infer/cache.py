"""Preallocated, static-shape KV caches for jitted decode.

The reference plumbs caches but never exercises them (llama3/LLaMA-jax.ipynb
cell 24 accepts `(cache, position)` yet cell 14's `generate` recomputes the
full prefix per token; deepseekv3 cell 40 rebuilds its MLA cache per token).
Here the cache is a first-class pytree with a fixed `max_len` so the decode
step compiles once and runs under `lax.scan`/`while_loop`.

Masking contract: slots >= the current length hold stale data; attention
must mask with `kv_index <= query_position` (ops.attention.causal_mask /
position-based masks), never rely on zeroed slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class KVCache:
    """Per-layer key/value cache, laid out (batch, max_len, n_kv_heads, head_dim)."""

    k: jax.Array
    v: jax.Array

    @classmethod
    def init(
        cls,
        batch: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        shape = (batch, max_len, n_kv_heads, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def max_len(self) -> int:
        return self.k.shape[1]


@struct.dataclass
class LatentCache:
    """MLA compressed-KV cache: stores latents (batch, max_len, latent_dim),
    not decompressed k/v — the point of multi-head latent attention
    (deepseekv3/deepseekv3.ipynb cell 25). One cache per layer, shared by
    all heads (the paper's layout; the reference instead threads a single
    cache through heads AND layers, growing it per head — a quirk documented
    in SURVEY.md §2.2 and deliberately not reproduced)."""

    c: jax.Array

    @classmethod
    def init(
        cls, batch: int, max_len: int, latent_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "LatentCache":
        return cls(c=jnp.zeros((batch, max_len, latent_dim), dtype))

    @property
    def max_len(self) -> int:
        return self.c.shape[1]


@struct.dataclass
class CPLatentCache:
    """Context-sharded MLA cache for decode under context parallelism
    (SURVEY.md §5 long-context row — the inference half of the CP story).

    Layout per context shard: `c_prompt` (B, s0_local, L) holds this
    shard's CONTIGUOUS prompt chunk — written in place by the ring prefill,
    so no resharding collective is ever needed — and `c_tail`
    (B, tail_len, L) holds the decoded tokens REPLICATED across the context
    axis (decode tokens are few; replicating them keeps the per-step write
    collective-free). Per-step attention computes shard-local logsumexp
    partials over c_prompt (plus c_tail on the last shard only, so the
    replicated tail is counted once) and combines them with one
    pmax + two psums over the context axis — the cache never moves.
    """

    c_prompt: jax.Array
    c_tail: jax.Array

    @classmethod
    def init(
        cls, batch: int, prompt_local: int, tail_len: int, latent_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "CPLatentCache":
        return cls(
            c_prompt=jnp.zeros((batch, prompt_local, latent_dim), dtype),
            c_tail=jnp.zeros((batch, tail_len, latent_dim), dtype),
        )


@struct.dataclass
class CPKVCache:
    """Context-sharded k/v cache for GQA/MHA decode under CP — same layout
    contract as CPLatentCache: prompt chunks stay sharded where the ring
    prefill produced them, decoded tokens are replicated in the tail."""

    k_prompt: jax.Array
    v_prompt: jax.Array
    k_tail: jax.Array
    v_tail: jax.Array

    @classmethod
    def init(
        cls, batch: int, prompt_local: int, tail_len: int, n_kv_heads: int,
        head_dim: int, dtype: jnp.dtype = jnp.bfloat16,
    ) -> "CPKVCache":
        pshape = (batch, prompt_local, n_kv_heads, head_dim)
        tshape = (batch, tail_len, n_kv_heads, head_dim)
        return cls(
            k_prompt=jnp.zeros(pshape, dtype), v_prompt=jnp.zeros(pshape, dtype),
            k_tail=jnp.zeros(tshape, dtype), v_tail=jnp.zeros(tshape, dtype),
        )


def validate_cp_cache(cache, expected_cls, prompt_len: int, s: int) -> None:
    """Shared trace-time guards for CP cached attention — one copy for MLA
    (models/deepseekv3.py) and the generic Attention (models/layers.py)."""
    if not isinstance(cache, expected_cls):
        raise TypeError(
            f"decode under context parallelism needs the context-sharded "
            f"{expected_cls.__name__} (model.init_cp_caches / "
            "infer.generate_cp); a plain per-shard cache would silently "
            "attend only local slots"
        )
    if prompt_len < 2:
        raise ValueError(
            "CP caches need >= 2 prompt slots per shard: a 1-slot "
            "chunk is indistinguishable from a decode step"
        )
    if s not in (1, prompt_len):
        raise ValueError(
            f"CP cached call must be the full local prompt chunk "
            f"({prompt_len} tokens, ring prefill) or a single decode "
            f"token; got {s}"
        )


def _cp_combine(
    scores_p: jax.Array,
    scores_t: jax.Array,
    vals: jax.Array,
    axis_name: str,
    spec: str,
) -> jax.Array:
    """Shared core of the two distributed softmax-combines below: one pmax
    + two psums over `axis_name`; `spec` is the value-contraction einsum."""
    scores = jnp.concatenate([scores_p, scores_t], axis=-1)
    m = jax.lax.pmax(jnp.max(scores, axis=-1, keepdims=True), axis_name)
    w = jnp.exp(scores - m)
    l = jax.lax.psum(jnp.sum(w, axis=-1, keepdims=True), axis_name)
    o = jax.lax.psum(
        jnp.einsum(spec, w, vals.astype(jnp.float32)), axis_name
    )
    return o / jnp.moveaxis(l, 1, 2)


def cp_cache_partial_softmax(
    scores_p: jax.Array,
    scores_t: jax.Array,
    vals: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Numerically-stable distributed softmax-combine for CP cached decode.

    scores_p (B, N, S, Tp) local-prompt scores (f32, already masked),
    scores_t (B, N, S, Tt) tail scores (masked to -inf on all but the
    counting shard), vals (B, Tp+Tt, L) the matching value rows. Returns
    (B, S, N, L) f32 — softmax over the GLOBAL slot set via one pmax and
    two psums over `axis_name`; per-shard work is a (S, T_local) matmul so
    the sharded cache never moves.
    """
    return _cp_combine(scores_p, scores_t, vals, axis_name, "bnst,btl->bsnl")


def cp_cache_partial_softmax_kv(
    scores_p: jax.Array,
    scores_t: jax.Array,
    vals: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Head-resolved variant of `cp_cache_partial_softmax` for CPKVCache:
    vals (B, Tp+Tt, N, H) (kv heads already repeated to N) -> (B, S, N, H)."""
    return _cp_combine(scores_p, scores_t, vals, axis_name, "bnst,btnh->bsnh")


def update_latent_cache(
    cache: LatentCache, c_new: jax.Array, index: jax.Array
) -> LatentCache:
    """Write latents (B, S, L) at sequence offset `index`."""
    return LatentCache(
        c=jax.lax.dynamic_update_slice(
            cache.c, c_new.astype(cache.c.dtype), (0, index, 0)
        )
    )


def update_kv_cache(
    cache: KVCache, k_new: jax.Array, v_new: jax.Array, index: jax.Array
) -> KVCache:
    """Write `k_new`/`v_new` (B, S, n_kv, H) into the cache at sequence offset
    `index` (scalar int array) and return the updated cache."""
    start = (0, index, 0, 0)
    return KVCache(
        k=jax.lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype), start),
        v=jax.lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype), start),
    )
