"""Preallocated, static-shape KV caches for jitted decode.

The reference plumbs caches but never exercises them (llama3/LLaMA-jax.ipynb
cell 24 accepts `(cache, position)` yet cell 14's `generate` recomputes the
full prefix per token; deepseekv3 cell 40 rebuilds its MLA cache per token).
Here the cache is a first-class pytree with a fixed `max_len` so the decode
step compiles once and runs under `lax.scan`/`while_loop`.

Masking contract: slots >= the current length hold stale data; attention
must mask with `kv_index <= query_position` (ops.attention.causal_mask /
position-based masks), never rely on zeroed slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class KVCache:
    """Per-layer key/value cache, laid out (batch, max_len, n_kv_heads, head_dim)."""

    k: jax.Array
    v: jax.Array

    @classmethod
    def init(
        cls,
        batch: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        shape = (batch, max_len, n_kv_heads, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def max_len(self) -> int:
        return self.k.shape[1]


def update_kv_cache(
    cache: KVCache, k_new: jax.Array, v_new: jax.Array, index: jax.Array
) -> KVCache:
    """Write `k_new`/`v_new` (B, S, n_kv, H) into the cache at sequence offset
    `index` (scalar int array) and return the updated cache."""
    start = (0, index, 0, 0)
    return KVCache(
        k=jax.lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype), start),
        v=jax.lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype), start),
    )
