"""Preallocated, static-shape KV caches for jitted decode.

The reference plumbs caches but never exercises them (llama3/LLaMA-jax.ipynb
cell 24 accepts `(cache, position)` yet cell 14's `generate` recomputes the
full prefix per token; deepseekv3 cell 40 rebuilds its MLA cache per token).
Here the cache is a first-class pytree with a fixed `max_len` so the decode
step compiles once and runs under `lax.scan`/`while_loop`.

Masking contract: slots >= the current length hold stale data; attention
must mask with `kv_index <= query_position` (ops.attention.causal_mask /
position-based masks), never rely on zeroed slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class KVCache:
    """Per-layer key/value cache, laid out (batch, max_len, n_kv_heads, head_dim)."""

    k: jax.Array
    v: jax.Array

    @classmethod
    def init(
        cls,
        batch: int,
        max_len: int,
        n_kv_heads: int,
        head_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "KVCache":
        shape = (batch, max_len, n_kv_heads, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))

    @property
    def max_len(self) -> int:
        return self.k.shape[1]


@struct.dataclass
class LatentCache:
    """MLA compressed-KV cache: stores latents (batch, max_len, latent_dim),
    not decompressed k/v — the point of multi-head latent attention
    (deepseekv3/deepseekv3.ipynb cell 25). One cache per layer, shared by
    all heads (the paper's layout; the reference instead threads a single
    cache through heads AND layers, growing it per head — a quirk documented
    in SURVEY.md §2.2 and deliberately not reproduced)."""

    c: jax.Array

    @classmethod
    def init(
        cls, batch: int, max_len: int, latent_dim: int,
        dtype: jnp.dtype = jnp.bfloat16,
    ) -> "LatentCache":
        return cls(c=jnp.zeros((batch, max_len, latent_dim), dtype))

    @property
    def max_len(self) -> int:
        return self.c.shape[1]


def update_latent_cache(
    cache: LatentCache, c_new: jax.Array, index: jax.Array
) -> LatentCache:
    """Write latents (B, S, L) at sequence offset `index`."""
    return LatentCache(
        c=jax.lax.dynamic_update_slice(
            cache.c, c_new.astype(cache.c.dtype), (0, index, 0)
        )
    )


def update_kv_cache(
    cache: KVCache, k_new: jax.Array, v_new: jax.Array, index: jax.Array
) -> KVCache:
    """Write `k_new`/`v_new` (B, S, n_kv, H) into the cache at sequence offset
    `index` (scalar int array) and return the updated cache."""
    start = (0, index, 0, 0)
    return KVCache(
        k=jax.lax.dynamic_update_slice(cache.k, k_new.astype(cache.k.dtype), start),
        v=jax.lax.dynamic_update_slice(cache.v, v_new.astype(cache.v.dtype), start),
    )
