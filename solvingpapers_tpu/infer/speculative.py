"""MTP self-speculative decoding for the DeepSeek-V3 family.

The reference trains MTP heads (deepseekv3.ipynb cells 33/46) but never
uses them at inference; real DeepSeek-V3 uses head k=1 for speculative
decoding, and this module implements that TPU-first: each loop iteration
runs ONE main forward over a 2-token chunk — the last accepted token plus
the MTP head's draft of the token after it — and the chunk's first logits
verify the draft for free. On acceptance the iteration commits TWO tokens
(the draft plus the chunk's second argmax); on rejection, one (the true
argmax). Greedy output is therefore IDENTICAL to plain `generate` —
speculation only changes how many forwards it takes
(tests/test_speculative.py pins the equality).

Mechanics worth noting:
  * The MTP head is a little autoregressive model over merged
    [norm(h_i), norm(emb(token_{i+1}))] reps, so it carries its OWN latent
    cache, prefilled alongside the main one (models.deepseekv3
    .mtp_head_apply).
  * On rejection the chunk's second cache slot (main AND mtp) holds
    garbage, but the next iteration's chunk starts at exactly that
    position and overwrites it before any attention can read it —
    position-based masking never exposes slots beyond the current token.
  * Greedy only: exact-match verification is lossless for argmax; the
    stochastic variant needs rejection-sampling corrections and is out of
    scope HERE. Batch 1 only: rows would otherwise advance at different
    rates and the contiguous cache write (one position per step) no
    longer holds. The SERVING engine lifts both limits:
    `serve/spec.py` + `ServeConfig(speculative="mtp")` run this module's
    head mechanics per slot under vmap inside the continuous-batching
    decode block (per-slot positions, traced accept counts) and verify
    stochastic slots with modified rejection sampling against the
    per-request truncated distributions. This module remains the one-shot
    batch-1 path (`cli sample --speculative [--spec-drafts 2]`).
  * Equality caveat (measured, not hypothetical): verification computes
    logits over a 2-3-token chunk while plain generate uses 1-token steps;
    XLA may re-associate the reductions differently, so bf16 argmax TIES
    can resolve differently between the two programs. On trained
    checkpoints (peaked logits) outputs match exactly — the bench pins
    this — and in f32 the equality tests are exact; an untrained bf16
    model decoding near-uniform logits for hundreds of steps can diverge
    at tie positions. Both outputs are valid greedy decodes of the model.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from solvingpapers_tpu.infer.cache import LatentCache


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "prefill_chunk", "n_drafts"),
)
def generate_speculative(
    model,
    params,
    prompt: jax.Array,
    *,
    max_new_tokens: int = 64,
    extra_variables: dict | None = None,
    prefill_chunk: int | None = None,
    n_drafts: int = 1,
):
    """Greedy decode with MTP-draft speculation.

    Returns (tokens (1, S0 + max_new_tokens), stats) where stats carries
    `forwards` (main model calls in the decode loop) and `accepted`
    (drafts that verified) — tokens/forward = 1 + accepted/forwards.
    Requires model.cfg.mtp_heads >= n_drafts and prompt batch 1.

    n_drafts=2 chains BOTH trained MTP heads: head 1's layer output feeds
    head 2 (exactly the training-time chaining, cell 33), so each
    iteration verifies a 3-token chunk [t, d1, d2] with accept-prefix
    semantics and commits up to 3 tokens per forward. Greedy output stays
    IDENTICAL to plain `generate` — committed tokens only ever come from
    the main model's argmax; drafts change speed, not content. One honest
    caveat, documented: head 2's cache column for the newest position is
    built from head 1's (unverified) draft embedding — a rejected draft
    leaves that one surviving slot draft-contaminated, which can only
    lower later acceptance, never change output.
    """
    if n_drafts not in (1, 2):
        raise ValueError(f"n_drafts must be 1 or 2, got {n_drafts}")
    cfg = model.cfg
    if getattr(cfg, "mtp_heads", 0) < n_drafts:
        raise ValueError(
            f"speculative decode with n_drafts={n_drafts} needs a model "
            f"with mtp_heads >= {n_drafts}"
        )
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(
            "speculative decode supports batch 1: rows accept drafts at "
            "different rates, which breaks the contiguous cache write"
        )
    if s0 < n_drafts + 1:
        raise ValueError(f"prompt must have at least {n_drafts + 1} tokens")
    # cache slack: the last chunk touches p + n_drafts
    total = s0 + max_new_tokens + n_drafts + 1
    limit = getattr(model, "max_positions", None)
    # chunk positions reach s0 + max_new + n_drafts - 2 (p tops out at
    # s0 + max_new - 2 entering the last iteration), so both the position
    # tables and the post-min cache must cover one slot PAST that — a bare
    # s0+max_new check would let the cache clamp shift the final chunk's
    # write one slot left and corrupt a committed token's latent
    if limit is not None and s0 + max_new_tokens + n_drafts - 1 > limit:
        raise ValueError(
            f"prompt+new+drafts = {s0 + max_new_tokens + n_drafts - 1} "
            f"exceeds the model's max positions {limit}"
        )
    total = min(total, limit) if limit is not None else total
    if prefill_chunk is None and s0 > 4096:
        prefill_chunk = 2048  # match generate()'s auto-chunk policy

    variables = {"params": params, **(extra_variables or {})}
    moe_state = variables.get("moe_state", {})
    from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

    caches = model.init_caches(1, total)
    mtp_cache = LatentCache.init(
        1, total, cfg.latent_dim + cfg.rope_dim, cfg.compute_dtype
    )

    # ---- prefill the main caches, collecting the post-norm hiddens
    hs = []
    chunk_size = prefill_chunk or s0
    logits = None
    for start in range(0, s0, chunk_size):
        end = min(start + chunk_size, s0)
        tok = jax.lax.slice_in_dim(prompt, start, end, axis=1)
        positions = jnp.broadcast_to(jnp.arange(start, end), (1, end - start))
        (logits, h), caches = model.apply(
            variables, tok, positions=positions, caches=caches,
            deterministic=True, attend_len=end, return_hidden=True,
        )
        hs.append(h)
    h_all = jnp.concatenate(hs, axis=1)  # (1, s0, D)

    # ---- prefill the MTP head's cache over positions [0, s0-1) (the
    # next-token embeddings are the prompt itself there) — chunked like the
    # main prefill so long prompts neither hit the flash kernel's q-block
    # limit nor materialize an (s0, s0) dense score tensor. With
    # n_drafts=2, collect head 1's layer output y1 — it is head 2's input
    # stream (the training-time chaining, cell 33).
    y1s = []
    for start in range(0, s0 - 1, chunk_size):
        end = min(start + chunk_size, s0 - 1)
        _, y1, mtp_cache, _ = mtp_head_apply(
            cfg, params, moe_state, h_all[:, start:end],
            prompt[:, start + 1 : end + 1],
            jnp.broadcast_to(jnp.arange(start, end), (1, end - start)),
            cache=mtp_cache, attend_len=end,
        )
        y1s.append(y1)

    t1 = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)  # (1,)
    # bootstrap draft at position s0-1 (h of the prompt's last token +
    # the embedding of the just-decoded t1) -> predicts position s0+1
    g, y1_last, mtp_cache, _ = mtp_head_apply(
        cfg, params, moe_state, h_all[:, -1:], t1[:, None],
        jnp.full((1, 1), s0 - 1), cache=mtp_cache,
    )
    d0 = jnp.argmax(g[:, -1], axis=-1).astype(prompt.dtype)

    mtp2_cache = d2_0 = None
    if n_drafts == 2:
        # head 2's cache over positions [0, s0-2): merged(y1_i,
        # emb(token_{i+2})) — both verified there
        mtp2_cache = LatentCache.init(
            1, total, cfg.latent_dim + cfg.rope_dim, cfg.compute_dtype
        )
        y1_all = jnp.concatenate([*y1s, y1_last], axis=1)  # (1, s0, D)
        for start in range(0, s0 - 2, chunk_size):
            end = min(start + chunk_size, s0 - 2)
            _, _, mtp2_cache, _ = mtp_head_apply(
                cfg, params, moe_state, y1_all[:, start:end],
                prompt[:, start + 2 : end + 2],
                jnp.broadcast_to(jnp.arange(start, end), (1, end - start)),
                cache=mtp2_cache, attend_len=end, head=2,
            )
        # bootstrap head 2 over columns [s0-2, s0-1]: next tokens are the
        # decoded t1 (@s0, verified) and head 1's draft d0 (@s0+1) —
        # column s0-1's cache slot carries the documented draft taint
        g2, _, mtp2_cache, _ = mtp_head_apply(
            cfg, params, moe_state, y1_all[:, s0 - 2 : s0],
            jnp.stack([t1[0], d0[0]])[None, :],
            jnp.broadcast_to(jnp.arange(s0 - 2, s0), (1, 2)),
            cache=mtp2_cache, head=2,
        )
        d2_0 = jnp.argmax(g2[:, -1], axis=-1).astype(prompt.dtype)

    out = jnp.zeros((max_new_tokens + n_drafts + 1,), prompt.dtype)
    out = out.at[0].set(t1[0])

    if n_drafts == 2:
        return _speculative_loop_2(
            model, variables, cfg, params, moe_state, prompt, t1, d0, d2_0,
            caches, mtp_cache, mtp2_cache, out, s0, max_new_tokens,
        )

    def cond(carry):
        return carry[3] < max_new_tokens

    def body(carry):
        t, d, p, count, caches, mtp_cache, out, forwards, accepts = carry
        chunk = jnp.stack([t[0], d[0]])[None, :]  # (1, 2)
        positions = jnp.stack([p, p + 1])[None, :]
        (l, h2), caches = model.apply(
            variables, chunk, positions=positions, caches=caches,
            deterministic=True, return_hidden=True,
        )
        true_next = jnp.argmax(l[:, 0], axis=-1).astype(t.dtype)  # tok @ p+1
        t2 = jnp.argmax(l[:, 1], axis=-1).astype(t.dtype)  # tok @ p+2 if ok
        ok = (true_next[0] == d[0])

        out1 = jax.lax.dynamic_update_index_in_dim(out, true_next[0], count, 0)
        out2 = jax.lax.dynamic_update_index_in_dim(out1, t2[0], count + 1, 0)
        out = jnp.where(ok, out2, out1)

        # MTP head over the same 2 columns: merged_p uses the TRUE token at
        # p+1 (true_next); merged_{p+1} uses t2 — garbage on rejection, but
        # that cache slot is overwritten by the next iteration's chunk
        next_toks = jnp.stack([true_next[0], t2[0]])[None, :]
        g2, _, mtp_cache, _ = mtp_head_apply(
            cfg, params, moe_state, h2, next_toks, positions,
            cache=mtp_cache,
        )
        draft = jnp.where(
            ok,
            jnp.argmax(g2[:, 1], axis=-1),
            jnp.argmax(g2[:, 0], axis=-1),
        ).astype(t.dtype)

        t_next = jnp.where(ok, t2, true_next)
        p_next = p + 1 + ok.astype(p.dtype)
        count_next = count + 1 + ok.astype(count.dtype)
        return (t_next, draft, p_next, count_next, caches, mtp_cache, out,
                forwards + 1, accepts + ok.astype(forwards.dtype))

    carry0 = (t1, d0, jnp.asarray(s0), jnp.asarray(1), caches, mtp_cache,
              out, jnp.asarray(0), jnp.asarray(0))
    _, _, _, _, _, _, out, forwards, accepts = jax.lax.while_loop(
        cond, body, carry0
    )
    tokens = jnp.concatenate([prompt, out[None, :max_new_tokens]], axis=1)
    return tokens, {"forwards": forwards, "accepted": accepts}


def _speculative_loop_2(model, variables, cfg, params, moe_state, prompt,
                        t1, d1_0, d2_0, caches, mtp1_cache, mtp2_cache, out,
                        s0, max_new_tokens):
    """Decode loop for n_drafts=2: verify 3-token chunks [t, d1, d2] with
    accept-prefix semantics (a = 0, 1 or 2 accepted drafts), committing
    1 + a tokens per main forward (cap 3). Draft refresh chains the heads:
    head 1 redrafts from the chunk's hiddens at column a, head 2 from
    head 1's layer output with head 1's fresh draft as its next-token
    embedding at column a."""
    from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

    def cond(carry):
        return carry[4] < max_new_tokens

    def body(carry):
        t, d1, d2, p, count, caches, c1, c2, out, forwards, accepts = carry
        chunk = jnp.stack([t[0], d1[0], d2[0]])[None, :]  # (1, 3)
        positions = (p + jnp.arange(3))[None, :]
        (l, h3), caches = model.apply(
            variables, chunk, positions=positions, caches=caches,
            deterministic=True, return_hidden=True,
        )
        true1 = jnp.argmax(l[:, 0], axis=-1).astype(t.dtype)  # tok @ p+1
        true2 = jnp.argmax(l[:, 1], axis=-1).astype(t.dtype)  # @ p+2 if ok1
        t3 = jnp.argmax(l[:, 2], axis=-1).astype(t.dtype)     # @ p+3 if ok2
        ok1 = true1[0] == d1[0]
        ok2 = ok1 & (true2[0] == d2[0])
        a = ok1.astype(jnp.int32) + ok2.astype(jnp.int32)

        out1 = jax.lax.dynamic_update_index_in_dim(out, true1[0], count, 0)
        out2 = jax.lax.dynamic_update_index_in_dim(out1, true2[0], count + 1, 0)
        out2 = jnp.where(ok1, out2, out1)
        out3 = jax.lax.dynamic_update_index_in_dim(out2, t3[0], count + 2, 0)
        out = jnp.where(ok2, out3, out2)

        # head 1 over the 3 columns; its next-token stream is the main
        # model's verified argmaxes (garbage columns are either never
        # selected or overwritten by the next chunk)
        next1 = jnp.stack([true1[0], true2[0], t3[0]])[None, :]
        g1, y1, c1, _ = mtp_head_apply(
            cfg, params, moe_state, h3, next1, positions, cache=c1,
        )
        d1n = jnp.argmax(jnp.take(g1[0], a, axis=0), axis=-1).astype(t.dtype)

        # head 2 over the same columns on head 1's layer output; column a
        # (the newest surviving slot) embeds head 1's FRESH draft — the
        # only token at that offset that exists yet (documented taint)
        next2 = jnp.stack([true2[0], t3[0], t3[0]])
        next2 = next2.at[a].set(d1n)
        g2, _, c2, _ = mtp_head_apply(
            cfg, params, moe_state, y1, next2[None, :], positions,
            cache=c2, head=2,
        )
        d2n = jnp.argmax(jnp.take(g2[0], a, axis=0), axis=-1).astype(t.dtype)

        t_next = jnp.take(jnp.stack([true1[0], true2[0], t3[0]]), a)[None]
        p_next = p + 1 + a.astype(p.dtype)
        count_next = count + 1 + a.astype(count.dtype)
        return (t_next, d1n[None], d2n[None], p_next, count_next, caches,
                c1, c2, out, forwards + 1, accepts + a.astype(forwards.dtype))

    carry0 = (t1, d1_0, d2_0, jnp.asarray(s0), jnp.asarray(1), caches,
              mtp1_cache, mtp2_cache, out, jnp.asarray(0), jnp.asarray(0))
    res = jax.lax.while_loop(cond, body, carry0)
    out, forwards, accepts = res[8], res[9], res[10]
    tokens = jnp.concatenate([prompt, out[None, :max_new_tokens]], axis=1)
    return tokens, {"forwards": forwards, "accepted": accepts}
