"""MTP self-speculative decoding for the DeepSeek-V3 family.

The reference trains MTP heads (deepseekv3.ipynb cells 33/46) but never
uses them at inference; real DeepSeek-V3 uses head k=1 for speculative
decoding, and this module implements that TPU-first: each loop iteration
runs ONE main forward over a 2-token chunk — the last accepted token plus
the MTP head's draft of the token after it — and the chunk's first logits
verify the draft for free. On acceptance the iteration commits TWO tokens
(the draft plus the chunk's second argmax); on rejection, one (the true
argmax). Greedy output is therefore IDENTICAL to plain `generate` —
speculation only changes how many forwards it takes
(tests/test_speculative.py pins the equality).

Mechanics worth noting:
  * The MTP head is a little autoregressive model over merged
    [norm(h_i), norm(emb(token_{i+1}))] reps, so it carries its OWN latent
    cache, prefilled alongside the main one (models.deepseekv3
    .mtp_head_apply).
  * On rejection the chunk's second cache slot (main AND mtp) holds
    garbage, but the next iteration's chunk starts at exactly that
    position and overwrites it before any attention can read it —
    position-based masking never exposes slots beyond the current token.
  * Greedy only: exact-match verification is lossless for argmax; the
    stochastic variant needs rejection-sampling corrections and is out of
    scope. Batch 1 only: rows would otherwise advance at different rates
    and the contiguous cache write (one position per step) no longer
    holds.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from solvingpapers_tpu.infer.cache import LatentCache


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "prefill_chunk"),
)
def generate_speculative(
    model,
    params,
    prompt: jax.Array,
    *,
    max_new_tokens: int = 64,
    extra_variables: dict | None = None,
    prefill_chunk: int | None = None,
):
    """Greedy decode with MTP-draft speculation.

    Returns (tokens (1, S0 + max_new_tokens), stats) where stats carries
    `forwards` (main model calls in the decode loop) and `accepted`
    (drafts that verified) — tokens/forward = 1 + accepted/forwards.
    Requires model.cfg.mtp_heads >= 1 and prompt batch 1.
    """
    cfg = model.cfg
    if getattr(cfg, "mtp_heads", 0) < 1:
        raise ValueError("speculative decode needs a model with mtp_heads >= 1")
    b, s0 = prompt.shape
    if b != 1:
        raise ValueError(
            "speculative decode supports batch 1: rows accept drafts at "
            "different rates, which breaks the contiguous cache write"
        )
    if s0 < 2:
        raise ValueError("prompt must have at least 2 tokens")
    total = s0 + max_new_tokens + 2  # cache slack: the last chunk touches p+1
    limit = getattr(model, "max_positions", None)
    # positions never exceed s0 + max_new - 1 (p = s0 + count - 1 and the
    # loop stops at count == max_new), so full-context decodes that plain
    # generate accepts pass here too; only the CACHE carries +2 slack
    if limit is not None and s0 + max_new_tokens > limit:
        raise ValueError(
            f"prompt+new = {s0 + max_new_tokens} exceeds the model's "
            f"max positions {limit}"
        )
    total = min(total, limit) if limit is not None else total
    if prefill_chunk is None and s0 > 4096:
        prefill_chunk = 2048  # match generate()'s auto-chunk policy

    variables = {"params": params, **(extra_variables or {})}
    moe_state = variables.get("moe_state", {})
    from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

    caches = model.init_caches(1, total)
    mtp_cache = LatentCache.init(
        1, total, cfg.latent_dim + cfg.rope_dim, cfg.compute_dtype
    )

    # ---- prefill the main caches, collecting the post-norm hiddens
    hs = []
    chunk_size = prefill_chunk or s0
    logits = None
    for start in range(0, s0, chunk_size):
        end = min(start + chunk_size, s0)
        tok = jax.lax.slice_in_dim(prompt, start, end, axis=1)
        positions = jnp.broadcast_to(jnp.arange(start, end), (1, end - start))
        (logits, h), caches = model.apply(
            variables, tok, positions=positions, caches=caches,
            deterministic=True, attend_len=end, return_hidden=True,
        )
        hs.append(h)
    h_all = jnp.concatenate(hs, axis=1)  # (1, s0, D)

    # ---- prefill the MTP head's cache over positions [0, s0-1) (the
    # next-token embeddings are the prompt itself there) — chunked like the
    # main prefill so long prompts neither hit the flash kernel's q-block
    # limit nor materialize an (s0, s0) dense score tensor
    for start in range(0, s0 - 1, chunk_size):
        end = min(start + chunk_size, s0 - 1)
        _, _, mtp_cache, _ = mtp_head_apply(
            cfg, params, moe_state, h_all[:, start:end],
            prompt[:, start + 1 : end + 1],
            jnp.broadcast_to(jnp.arange(start, end), (1, end - start)),
            cache=mtp_cache, attend_len=end,
        )

    t1 = jnp.argmax(logits[:, -1], axis=-1).astype(prompt.dtype)  # (1,)
    # bootstrap draft at position s0-1 (h of the prompt's last token +
    # the embedding of the just-decoded t1) -> predicts position s0+1
    g, _, mtp_cache, _ = mtp_head_apply(
        cfg, params, moe_state, h_all[:, -1:], t1[:, None],
        jnp.full((1, 1), s0 - 1), cache=mtp_cache,
    )
    d0 = jnp.argmax(g[:, -1], axis=-1).astype(prompt.dtype)

    out = jnp.zeros((max_new_tokens + 2,), prompt.dtype)
    out = out.at[0].set(t1[0])

    def cond(carry):
        return carry[3] < max_new_tokens

    def body(carry):
        t, d, p, count, caches, mtp_cache, out, forwards, accepts = carry
        chunk = jnp.stack([t[0], d[0]])[None, :]  # (1, 2)
        positions = jnp.stack([p, p + 1])[None, :]
        (l, h2), caches = model.apply(
            variables, chunk, positions=positions, caches=caches,
            deterministic=True, return_hidden=True,
        )
        true_next = jnp.argmax(l[:, 0], axis=-1).astype(t.dtype)  # tok @ p+1
        t2 = jnp.argmax(l[:, 1], axis=-1).astype(t.dtype)  # tok @ p+2 if ok
        ok = (true_next[0] == d[0])

        out1 = jax.lax.dynamic_update_index_in_dim(out, true_next[0], count, 0)
        out2 = jax.lax.dynamic_update_index_in_dim(out1, t2[0], count + 1, 0)
        out = jnp.where(ok, out2, out1)

        # MTP head over the same 2 columns: merged_p uses the TRUE token at
        # p+1 (true_next); merged_{p+1} uses t2 — garbage on rejection, but
        # that cache slot is overwritten by the next iteration's chunk
        next_toks = jnp.stack([true_next[0], t2[0]])[None, :]
        g2, _, mtp_cache, _ = mtp_head_apply(
            cfg, params, moe_state, h2, next_toks, positions,
            cache=mtp_cache,
        )
        draft = jnp.where(
            ok,
            jnp.argmax(g2[:, 1], axis=-1),
            jnp.argmax(g2[:, 0], axis=-1),
        ).astype(t.dtype)

        t_next = jnp.where(ok, t2, true_next)
        p_next = p + 1 + ok.astype(p.dtype)
        count_next = count + 1 + ok.astype(count.dtype)
        return (t_next, draft, p_next, count_next, caches, mtp_cache, out,
                forwards + 1, accepts + ok.astype(forwards.dtype))

    carry0 = (t1, d0, jnp.asarray(s0), jnp.asarray(1), caches, mtp_cache,
              out, jnp.asarray(0), jnp.asarray(0))
    _, _, _, _, _, _, out, forwards, accepts = jax.lax.while_loop(
        cond, body, carry0
    )
    tokens = jnp.concatenate([prompt, out[None, :max_new_tokens]], axis=1)
    return tokens, {"forwards": forwards, "accepted": accepts}
