"""Jitted autoregressive generation.

Replaces the reference's four unjitted python token loops (gpt cell 19,
llama3 cell 14, gemma cell 20, deepseekv3 cell 40 — all of which re-run
the full forward on the growing prefix; llama3 plumbs a KV cache but never
passes it) with one compiled prefill + lax.scan decode over preallocated
caches. Works with any model exposing
  __call__(tokens, *, positions, caches, deterministic) -> (logits, caches)
  init_caches(batch, max_len) -> list[cache pytree]
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from solvingpapers_tpu import ops


@functools.partial(
    jax.jit,
    static_argnames=("model", "max_new_tokens", "sampler", "max_len",
                     "prefill_chunk"),
)
def generate(
    model,
    params,
    prompt: jax.Array,
    rng: jax.Array,
    *,
    max_new_tokens: int = 64,
    sampler: Callable = ops.sample_greedy,
    max_len: int | None = None,
    extra_variables: dict | None = None,
    eos_id: int | None = None,
    prefill_chunk: int | None = None,
) -> jax.Array:
    """Generate `max_new_tokens` continuations of `prompt` (B, S0) int32.

    Returns (B, S0 + max_new_tokens). The whole function is one XLA program:
    a prefill pass filling the caches, then a scan of single-token steps.
    `extra_variables` carries non-param collections (e.g. DeepSeekV3's
    'moe_state' routing bias). `eos_id` gives deepseekv3 cell 40's
    stop-on-EOS semantics in static-shape form: once a sequence samples
    EOS, all its later positions are EOS (the scan itself always runs
    max_new_tokens steps — XLA needs static shapes).

    Prefill passes a STATIC `attend_len` to the model, so cached attention
    runs end-aligned causal over only the written cache slots (the Pallas
    flash kernel for use_flash models) instead of masked dense scores over
    the whole preallocated cache — this is what makes 16k-prompt prefill
    feasible (the dense path would materialize (B, N, S0, max_len) probs).
    `prefill_chunk` bounds prefill activation memory further by feeding the
    prompt in chunks: chunk i attends to cache slots [0, end_i) with the
    same end-aligned kernel call, writing as it goes.
    """
    b, s0 = prompt.shape
    total = s0 + max_new_tokens
    if max_len is None:
        max_len = total
    if total > max_len:
        raise ValueError(f"prompt+new tokens {total} exceed cache max_len {max_len}")
    limit = getattr(model, "max_positions", None)
    if limit is not None and total > limit:
        raise ValueError(
            f"prompt+new tokens {total} exceed the model's max positions {limit}"
        )

    caches = model.init_caches(b, max_len)
    variables = {"params": params, **(extra_variables or {})}
    if prefill_chunk is None and s0 > 4096:
        # auto-chunk long prompts (the CLI's cmd_sample default): a single
        # >4096-token prefill would raise from the flash kernel's
        # _pick_block_q when s0 has no 128-divisible block, and unchunked
        # activation memory grows with s0 regardless
        prefill_chunk = 2048
    if prefill_chunk is None or s0 <= prefill_chunk:
        positions = jnp.broadcast_to(jnp.arange(s0), (b, s0))
        logits, caches = model.apply(
            variables, prompt, positions=positions, caches=caches,
            deterministic=True, attend_len=s0,
        )
    else:
        # python loop = unrolled chunks with static slice bounds; the last
        # (possibly ragged) chunk just compiles one more layer shape
        for start in range(0, s0, prefill_chunk):
            end = min(start + prefill_chunk, s0)
            chunk = jax.lax.slice_in_dim(prompt, start, end, axis=1)
            positions = jnp.broadcast_to(
                jnp.arange(start, end), (b, end - start)
            )
            logits, caches = model.apply(
                variables, chunk, positions=positions, caches=caches,
                deterministic=True, attend_len=end,
            )
    rng, sub = jax.random.split(rng)
    first_tok = sampler(logits[:, -1], sub).astype(prompt.dtype)
    done0 = (
        first_tok == eos_id if eos_id is not None else jnp.zeros((b,), jnp.bool_)
    )
    if max_new_tokens == 1:
        return jnp.concatenate([prompt, first_tok[:, None]], axis=1)

    def body(carry, _):
        tok, pos, caches, rng, done = carry
        logits, caches = model.apply(
            variables,
            tok[:, None],
            positions=jnp.broadcast_to(pos[None, None], (b, 1)),
            caches=caches,
            deterministic=True,
        )
        rng, sub = jax.random.split(rng)
        new_tok = sampler(logits[:, -1], sub).astype(tok.dtype)
        if eos_id is not None:
            new_tok = jnp.where(done, jnp.asarray(eos_id, tok.dtype), new_tok)
            done = done | (new_tok == eos_id)
        return (new_tok, pos + 1, caches, rng, done), new_tok

    # one forward per emitted token: t0 from prefill, t1..t_{n-1} from the scan
    _, toks = jax.lax.scan(
        body, (first_tok, jnp.asarray(s0), caches, rng, done0), None,
        length=max_new_tokens - 1,
    )
    generated = jnp.concatenate([first_tok[:, None], jnp.moveaxis(toks, 0, 1)], axis=1)
    return jnp.concatenate([prompt, generated], axis=1)


@functools.lru_cache(maxsize=32)
def _cp_generate_program(model, mesh, s0_loc, max_new_tokens, sampler, eos_id):
    """Compiled prefill+decode program for `generate_cp`, cached so repeat
    calls with the same (model, mesh, shapes) don't retrace/recompile."""
    from jax.sharding import PartitionSpec as P

    def body(variables, prompt_local, rng):
        b = prompt_local.shape[0]
        cp_size = jax.lax.psum(1, "context")  # static under shard_map
        s0 = s0_loc * cp_size
        caches = model.init_cp_caches(b, s0_loc, max_new_tokens)
        # ring prefill; positions default to global inside the shard_map
        logits, caches = model.apply(
            variables, prompt_local, caches=caches, deterministic=True,
        )
        idx = jax.lax.axis_index("context")
        # the last GLOBAL token's logits live on the last shard — replicate
        last = jax.lax.psum(
            jnp.where(idx == cp_size - 1, logits[:, -1], 0.0), "context"
        )
        rng, sub = jax.random.split(rng)
        first_tok = sampler(last, sub).astype(prompt_local.dtype)
        done0 = (
            first_tok == eos_id if eos_id is not None
            else jnp.zeros((b,), jnp.bool_)
        )

        def step(carry, _):
            tok, pos, caches, rng, done = carry
            logits, caches = model.apply(
                variables, tok[:, None],
                positions=jnp.broadcast_to(pos[None, None], (b, 1)),
                caches=caches, deterministic=True,
            )
            rng, sub = jax.random.split(rng)
            new_tok = sampler(logits[:, -1], sub).astype(tok.dtype)
            if eos_id is not None:
                new_tok = jnp.where(
                    done, jnp.asarray(eos_id, tok.dtype), new_tok
                )
                done = done | (new_tok == eos_id)
            return (new_tok, pos + 1, caches, rng, done), new_tok

        if max_new_tokens == 1:
            return first_tok[:, None]
        _, toks = jax.lax.scan(
            step, (first_tok, jnp.asarray(s0), caches, rng, done0), None,
            length=max_new_tokens - 1,
        )
        return jnp.concatenate(
            [first_tok[:, None], jnp.moveaxis(toks, 0, 1)], axis=1
        )

    # check_vma off: the MoE stats path pmean/psums over axes the decode
    # inputs are replicated across (a vma type error, numerically a no-op)
    from solvingpapers_tpu.sharding.pipeline import shard_map_compat

    return jax.jit(
        shard_map_compat(
            body, mesh=mesh,
            in_specs=(P(), P(None, "context"), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def generate_cp(
    model,
    params,
    prompt: jax.Array,
    rng: jax.Array,
    mesh,
    *,
    max_new_tokens: int = 64,
    sampler: Callable = ops.sample_greedy,
    extra_variables: dict | None = None,
    eos_id: int | None = None,
) -> jax.Array:
    """Context-parallel generation: long-context decode beyond one chip
    (SURVEY.md §5 long-context row — the inference half of the CP story).

    The prompt is sharded over `mesh`'s 'context' axis; prefill is the ring
    attention pass writing each shard's contiguous chunk into its
    context-sharded cache slice (infer.cache.CPLatentCache — the ≥32k
    prompt cache never leaves its shard), then each decode step is a
    replicated single-token forward whose attention combines shard-local
    logsumexp partials with one pmax + two psums per layer. The model must
    be built with context_parallel=True and expose
    `init_cp_caches(batch, prompt_local, tail_len)`; `mesh` must carry the
    framework's standard axes (MeshConfig) with context = the shard count.

    Returns (B, S0 + max_new_tokens), same contract as `generate`.
    """
    b, s0 = prompt.shape
    cp = mesh.shape["context"]
    if s0 % cp:
        raise ValueError(f"prompt length {s0} not divisible by context={cp}")
    s0_loc = s0 // cp
    if s0_loc < 2:
        # a 1-token local chunk is indistinguishable from a decode step in
        # the model's cached dispatch — and a 1-token-per-shard prompt has
        # no business being context-parallel anyway
        raise ValueError(
            f"prompt length {s0} gives a 1-token shard on context={cp}; "
            "CP decode needs >= 2 prompt tokens per shard (use `generate`)"
        )
    limit = getattr(model, "max_positions", None)
    if limit is not None and s0 + max_new_tokens > limit:
        raise ValueError(
            f"prompt+new tokens {s0 + max_new_tokens} exceed the model's "
            f"max positions {limit}"
        )
    program = _cp_generate_program(
        model, mesh, s0_loc, max_new_tokens, sampler, eos_id
    )
    variables = {"params": params, **(extra_variables or {})}
    generated = program(variables, prompt, rng)
    return jnp.concatenate([prompt, generated.astype(prompt.dtype)], axis=1)
