"""Jitted inference: preallocated KV/latent caches + prefill/decode loops."""

from solvingpapers_tpu.infer.cache import (
    CPKVCache,
    CPLatentCache,
    KVCache,
    LatentCache,
    update_kv_cache,
    update_latent_cache,
)
from solvingpapers_tpu.infer.decode import generate, generate_cp
from solvingpapers_tpu.infer.speculative import generate_speculative  # noqa: E402,F401
