"""Jitted inference: preallocated KV/latent caches + prefill/decode loops."""

from solvingpapers_tpu.infer.cache import KVCache, update_kv_cache
from solvingpapers_tpu.infer.decode import generate
