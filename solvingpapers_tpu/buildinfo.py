"""Process identity for observability surfaces: version, git sha, uptime.

A scraped replica is anonymous without this — ROADMAP item 2's
per-replica `/statusz` aggregation needs to know WHICH build and WHICH
jax it is talking to before any of its numbers mean anything, and the
bench provenance stamp (serve/bench.py) needs the same facts so a
BENCH_serve.json entry stays identifiable after a rebase. One module so
the two surfaces cannot drift.

`build_info()` is cheap after the first call (git sha and versions are
cached; only uptime is live) and never raises: a missing git binary, a
tarball install, or an uninitialized jax backend degrade to None
fields, not a 500 from `/statusz`.
"""

from __future__ import annotations

import functools
import os
import subprocess
import sys
import time

__all__ = ["build_info", "git_sha"]

# process start, stamped at first import (the engine imports this before
# serving starts, so "uptime" is serving-process age for all practical
# purposes)
_START_MONOTONIC = time.monotonic()
_START_UNIX = time.time()


@functools.lru_cache(maxsize=1)
def git_sha() -> str | None:
    """The repo HEAD this process is running from, or None when the
    package runs outside a git checkout (wheel/tarball installs)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    if out.returncode != 0:
        return None
    sha = out.stdout.strip()
    return sha or None


@functools.lru_cache(maxsize=1)
def _static_info() -> dict:
    from solvingpapers_tpu import __version__

    info: dict = {
        "package": "solvingpapers_tpu",
        "version": __version__,
        "git_sha": git_sha(),
        "python": sys.version.split()[0],
        "pid": os.getpid(),
        "started_unix": round(_START_UNIX, 3),
    }
    try:
        import jax

        info["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        info["jax"] = None
    try:
        import jaxlib

        info["jaxlib"] = getattr(jaxlib, "__version__", None)
    except Exception:
        info["jaxlib"] = None
    try:
        import jax

        dev = jax.devices()[0]
        info["platform"] = dev.platform
        info["device_kind"] = dev.device_kind
        info["n_devices"] = len(jax.devices())
    except Exception:
        info["platform"] = None
        info["device_kind"] = None
        info["n_devices"] = None
    return info


def build_info() -> dict:
    """The /statusz `build` section: static identity + live uptime."""
    return {
        **_static_info(),
        "uptime_s": round(time.monotonic() - _START_MONOTONIC, 3),
    }
