"""Device-mesh construction with standardized axis names.

Axes (SURVEY.md §2.3/§5):
  data   — pure data parallelism (batch split, params replicated)
  fsdp   — fully-sharded data parallelism (batch AND params split; XLA
           all-gathers params on use, reduce-scatters grads)
  model  — tensor parallelism (attention heads / FFN hidden)
  expert — expert parallelism for MoE all_to_all dispatch

Batches are sharded over (data, fsdp) jointly; parameters over
(fsdp, model); MoE experts over expert; the sequence axis over context
(ring attention / Ulysses — both in sharding/ring_attention.py). On a
single chip every axis has size 1 and all of this compiles to a no-op.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("data", "fsdp", "model", "expert", "context", "pipe")

# The mesh a GSPMD-partitioned model is currently tracing under (set by the
# Trainer around its non-shard_map step/init bodies). pallas_call is opaque
# to GSPMD — without this, a use_flash model under a >1-device mesh would
# silently all-gather its attention operands; with it, apply_flash_attention
# routes through the shard_map-wrapped kernels.sharded_flash_attention.
# Inside CP/PP shard_map bodies this stays None: operands there are already
# local, so the direct kernel call is correct.
_AMBIENT_MESH: contextvars.ContextVar[Mesh | None] = contextvars.ContextVar(
    "ambient_gspmd_mesh", default=None
)


@contextlib.contextmanager
def ambient_mesh(mesh: Mesh | None):
    """Mark `mesh` as the GSPMD mesh for code traced within this scope."""
    token = _AMBIENT_MESH.set(mesh)
    try:
        yield
    finally:
        _AMBIENT_MESH.reset(token)


def get_ambient_mesh() -> Mesh | None:
    return _AMBIENT_MESH.get()


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes; -1 means 'absorb all remaining devices' (exactly one allowed)."""

    data: int = -1
    fsdp: int = 1
    model: int = 1
    expert: int = 1
    context: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.model, self.expert, self.context,
                 self.pipe]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {sizes}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return tuple(sizes)


def create_mesh(
    config: MeshConfig | None = None, devices: list | None = None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    config = config or MeshConfig()
    sizes = config.resolve(len(devices))
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def mesh_axis_sizes(mesh: Mesh) -> dict[str, int]:
    """{axis_name: size} for a mesh — the lookup the engines and the
    mesh observatory repeat (pipe depth, data-shard count, ...)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def batch_spec(extra_dims: int = 1, context: bool = False) -> P:
    """PartitionSpec for a batch-leading array: batch over (data, fsdp);
    with `context`, the next (sequence) dim over the 'context' axis — the
    layout context-parallel training steps shard_map over."""
    if context and extra_dims >= 1:
        return P(("data", "fsdp"), "context", *([None] * (extra_dims - 1)))
    return P(("data", "fsdp"), *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 1, context: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims, context=context))
