"""Multi-host initialization + host-local data utilities.

SURVEY.md §2.3 "Multi-host / elastic" row: the reference has nothing; the
TPU-native path is `jax.distributed.initialize()` over DCN with slice-local
data loading. All meshes in this repo are built from `jax.devices()`
(global across hosts once initialized), so the existing pjit/GSPMD train
steps run multi-host unchanged; the pieces a multi-host launch needs are:

  * initialize() — idempotent wrapper over jax.distributed.initialize,
    reading the standard env (Cloud TPU autodetects; explicit args for
    other clusters);
  * host_batch_slice / host_seed — deterministic per-host data sharding
    (SURVEY.md hard part #6: seed-stable per host).
"""

from __future__ import annotations

import os

import jax

_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Idempotent jax.distributed.initialize. Returns True if a multi-host
    runtime was (or already is) initialized, False for single-process runs.

    On Cloud TPU pods all arguments autodetect; elsewhere pass them or set
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID.
    """
    global _initialized
    if _initialized:
        return True
    # NOTE: do not touch jax.process_count()/jax.devices() here — any such
    # call initializes the local XLA backend and forecloses distributed init
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        _initialized = True
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    explicit = coordinator_address is not None
    autodetectable = (
        "TPU_WORKER_HOSTNAMES" in os.environ
        or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
    )
    if not explicit and not autodetectable:
        return False  # single-process
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=(
                num_processes
                if num_processes is not None
                else _int_env("JAX_NUM_PROCESSES")
            ),
            # `or` would discard the coordinator's legitimate process_id=0
            process_id=(
                process_id if process_id is not None else _int_env("JAX_PROCESS_ID")
            ),
        )
    except (RuntimeError, ValueError) as e:
        # backend already initialized, or autodetection came up empty (e.g.
        # a single-host dev env that still sets TPU_* vars): stay
        # single-process rather than crash — but an explicit request is a
        # real configuration error
        if explicit:
            raise
        import warnings

        warnings.warn(f"skipping jax.distributed.initialize: {e}", stacklevel=2)
        return False
    _initialized = True
    return True


def _int_env(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def host_seed(base_seed: int) -> int:
    """Deterministic per-host seed (hard part #6): every host draws a
    disjoint, reproducible batch stream."""
    return base_seed * 1_000_003 + jax.process_index()


def host_batch_slice(global_batch_size: int) -> tuple[int, int]:
    """(host_batch_size, offset) for loading only this host's rows of a
    globally-batched array. Requires divisibility by process_count."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {n} hosts"
        )
    per = global_batch_size // n
    return per, per * jax.process_index()
