"""Parameter partition rules: path-pattern -> PartitionSpec.

A single rule table holds for the whole model zoo (SURVEY.md hard part #4)
by relying on the shared layer naming from models/layers.py:

  column-parallel kernels (qkv / q / kv / gate / up / fc, lm_head):
      (in, out) -> P('fsdp', 'model')   — out features over TP axis
  row-parallel kernels (out / down / proj):
      (in, out) -> P('model', 'fsdp')   — in features over TP axis
  embeddings: (vocab, dim) -> P(None, 'fsdp')
  everything else (norm scales, biases, pos tables): replicated

With mesh sizes fsdp=model=1 every spec degenerates to replication; with
fsdp>1 this is GSPMD FSDP (params gathered on use); with model>1 it is
Megatron-style TP — all from the same table.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec). First match wins; paths are '/'-joined key tuples.
LM_RULES: list[tuple[str, P]] = [
    (r"(qkv|q|kv|gate|up|fc|w_dkv|w_q)/kernel$", P("fsdp", "model")),
    (r"(out|down|proj|w_o)/kernel$", P("model", "fsdp")),
    (r"lm_head/kernel$", P("fsdp", "model")),
    (r"(tok_emb|embedding)/embedding$", P(None, "fsdp")),
    (r"pos_emb$", P(None, "fsdp")),
    (r".*", P()),  # norms, biases, scalars: replicated
]

GPT_RULES = LM_RULES  # shared naming makes the generic table sufficient


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def param_specs(params, rules: list[tuple[str, P]] = LM_RULES):
    """Map a params pytree to a pytree of PartitionSpec via first-match rules."""

    def spec_for(path, leaf):
        p = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, p):
                # never shard more dims than the leaf has
                if len(spec) > leaf.ndim:
                    return P(*spec[: leaf.ndim])
                return spec
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def param_shardings(mesh: Mesh, params, rules: list[tuple[str, P]] = LM_RULES):
    specs = param_specs(params, rules)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
