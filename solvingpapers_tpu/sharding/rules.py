"""Parameter partition rules: path-pattern -> PartitionSpec.

A single rule table holds for the whole model zoo (SURVEY.md hard part #4)
by relying on the shared layer naming from models/layers.py:

  column-parallel kernels (qkv / q / kv / gate / up / fc, lm_head):
      (in, out) -> P('fsdp', 'model')   — out features over TP axis
  row-parallel kernels (out / down / proj):
      (in, out) -> P('model', 'fsdp')   — in features over TP axis
  embeddings: (vocab, dim) -> P('fsdp', None)  — vocab-dim ZeRO (feature-dim
      sharding would propagate into the residual stream; see the table note)
  everything else (norm scales, biases, pos tables): replicated

With mesh sizes fsdp=model=1 every spec degenerates to replication; with
fsdp>1 this is GSPMD FSDP (params gathered on use); with model>1 it is
Megatron-style TP — all from the same table.
"""

from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec). First match wins; paths are '/'-joined key tuples.
LM_RULES: list[tuple[str, P]] = [
    # MLA raw weights (D|L, heads, head_dim): heads over the TP axis
    (r"mla/(w_q|w_k|w_v)$", P(None, "model", None)),
    # stacked MoE expert weights (E, in, out): experts over the expert axis
    (r"moe/(w1|w2)$", P("expert", "fsdp", "model")),
    (r"moe/w3$", P("expert", "model", "fsdp")),
    (r"(qkv|q|kv|gate|up|fc|w_dkv|w_q)/kernel$", P("fsdp", "model")),
    (r"(out|down|proj|w_o)/kernel$", P("model", "fsdp")),
    (r"lm_head/kernel$", P("fsdp", "model")),
    # vocab-dim ZeRO for embedding tables: feature-dim sharding propagates
    # a feature-sharded residual stream out of the lookup, which collides
    # with the batch sharding downstream and trips GSPMD's involuntary
    # full-rematerialization fallback (spmd_partitioner.cc:652) on the
    # lookup gather and its scatter transpose. Vocab-dim sharding keeps the
    # same 1/fsdp storage while the gather output is born unsharded on
    # features (partitioner masks + psums over the vocab shards).
    (r"(tok_emb|embedding)/embedding$", P("fsdp", None)),
    (r"pos_emb$", P("fsdp", None)),
    (r".*", P()),  # norms, biases, scalars: replicated
]

GPT_RULES = LM_RULES  # shared naming makes the generic table sufficient

# Pipeline-parallel models (models/gpt_pipe.py): stage-stacked decoder
# params live under a top-level 'stages' key whose leading dim is the stage
# axis — sharded over 'pipe' so each device stores only its stage. The
# rest of the table applies to the replicated embedding/norm/head.
# (^|/) rather than ^: rules are applied to whole TrainState trees, where
# the same leaves appear under params/stages/... and opt_state/.../stages/...
# — the optimizer moments shard per stage exactly like the params.
PP_RULES: list[tuple[str, P]] = [(r"(^|/)stages/", P("pipe"))] + LM_RULES


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def _axis_product(mesh: Mesh | None, entry) -> int:
    if mesh is None or entry is None:
        return 1
    names = entry if isinstance(entry, tuple) else (entry,)
    n = 1
    for name in names:
        n *= mesh.shape.get(name, 1)
    return n


def leaf_spec(path, leaf, rules: list[tuple[str, P]] = LM_RULES,
              mesh: Mesh | None = None) -> P:
    """First-match rule spec for one (path, leaf). When `mesh` is given,
    any dimension whose size is not divisible by the product of its
    assigned mesh axes degrades to replicated for that dim (e.g. a SwiGLU
    hidden of (2·4·D)//3 that lands on an odd size)."""
    p = _path_str(path)
    for pattern, spec in rules:
        if re.search(pattern, p):
            entries = list(spec[: leaf.ndim])  # never shard more dims than leaf
            entries = [
                e if leaf.shape[d] % _axis_product(mesh, e) == 0 else None
                for d, e in enumerate(entries)
            ]
            return P(*entries)
    return P()


def param_specs(params, rules: list[tuple[str, P]] = LM_RULES, mesh: Mesh | None = None):
    """Map a params pytree to a pytree of PartitionSpec via first-match rules."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: leaf_spec(path, leaf, rules, mesh), params
    )


def param_shardings(mesh: Mesh, params, rules: list[tuple[str, P]] = LM_RULES):
    specs = param_specs(params, rules, mesh=mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
