"""Ring attention: context parallelism over the `context` mesh axis.

Not present in the reference (max trained context is 256 tokens,
SURVEY.md §5 "Long-context — absent") — this is the capability the new
framework adds for sequences larger than one chip's HBM. Each device holds
a sequence shard of Q, K, V; K/V chunks rotate around the ring via
`lax.ppermute` over ICI while every device accumulates its queries' online
softmax (the blockwise/flash recurrence, so the full (S, S) score matrix
never exists anywhere).

Layout: BSNH shards inside shard_map. Causality is resolved from global
chunk positions (device i holds positions [i*S_loc, (i+1)*S_loc)); fully
masked chunks still traverse the ring (uniform schedule keeps the
collective static) but contribute zero mass.
"""

from __future__ import annotations

import functools

from solvingpapers_tpu.sharding.pipeline import shard_map_compat

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from solvingpapers_tpu.ops.attention import BIG_NEG, repeat_kv


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Per-shard ring attention body; call inside shard_map.

    q: local (B, S_loc, N, H) sequence shard; k, v: (B, S_loc, Nkv, H) with
    N % Nkv == 0 — GQA kv heads are repeated per ring step AFTER the
    transfer, so ppermute traffic carries only the Nkv heads. Returns the
    local (B, S_loc, N, H) output shard of exact softmax attention over the
    full sequence.
    """
    b, s_loc, n, h = q.shape
    n_kv = k.shape[2]
    if n % n_kv:
        raise ValueError(f"q heads {n} not a multiple of kv heads {n_kv}")
    group = n // n_kv
    if scale is None:
        scale = h**-0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    q32 = q.astype(jnp.float32) * scale
    q_pos = my_idx * s_loc + jnp.arange(s_loc)

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # ppermute sends to (j+1): after i steps we hold chunk (my_idx - i)
        src = (my_idx - i) % axis_size
        s_ = jnp.einsum(
            "bqnh,bknh->bnqk", q32, repeat_kv(k_cur, group).astype(jnp.float32)
        )
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            s_ = jnp.where(mask, s_, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(s_, axis=-1, keepdims=True))
        p = jnp.exp(s_ - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bnqk,bknh->bqnh", p, repeat_kv(v_cur, group).astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    # derive initial accumulators from q so they inherit its varying-axes
    # type (shard_map vma typing: plain zeros would be device-invariant)
    q_bnsh = jnp.moveaxis(q32, 1, 2)  # (B, N, S_loc, H)
    m0 = jnp.full_like(q_bnsh[..., :1], BIG_NEG)
    l0 = jnp.zeros_like(q_bnsh[..., :1])
    acc0 = jnp.zeros_like(q_bnsh)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(axis_size)
    )
    out = acc / jnp.maximum(l, 1e-30)  # (B, N, S_loc, H)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    axis_name: str = "context",
) -> jax.Array:
    """Full-array entry point: shards the sequence axis over `axis_name`
    (batch over data/fsdp) and runs the ring. q, k, v: (B, S, N, H) with
    S divisible by the context axis size."""
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = functools.partial(
        ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def _ring_merge(m, l, acc, o_c, lse_c):
    """Online-softmax merge of one chunk's flash output into the running
    (m, l, acc): o_c is the chunk-normalized output, lse_c its per-row
    logsumexp, so o_c * exp(lse_c - m_new) recovers the unnormalized
    accumulator exactly."""
    m_new = jnp.maximum(m, lse_c)
    alpha = jnp.exp(m - m_new)
    beta = jnp.exp(lse_c - m_new)
    l_new = l * alpha + beta
    acc_new = acc * alpha[..., None] + o_c * beta[..., None]
    return m_new, l_new, acc_new


# Knuth multiplicative stride: distinct (owner, chunk) pairs land far apart
# in the kernel's seed space (the kernel already offsets by block uid within
# one call; the pair stride decorrelates masks ACROSS ring steps/devices).
# Plain python int — a module-level jnp constant would initialize the XLA
# backend at import time and break jax.distributed.initialize (multi-host).
_SEED_STRIDE = -1640531527


def _chunk_seed(seed, my_idx, src, axis_size):
    """Per-(q-owner, kv-chunk) dropout seed — the backward ring MUST derive
    the identical value for the same chunk so masks regenerate exactly."""
    pair = (my_idx * axis_size + src).astype(jnp.int32)
    return seed + pair * jnp.int32(_SEED_STRIDE)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _ring_flash(q3, k3, v3, seed, axis_name, heads, scale, causal, blocks,
                dropout_rate, interpret):
    out, _ = _ring_flash_fwd_scan(q3, k3, v3, seed, axis_name, heads, scale,
                                  causal, blocks, dropout_rate, interpret)
    return out


def _ring_flash_fwd_scan(q3, k3, v3, seed, axis_name, heads, scale, causal,
                         blocks, dropout_rate, interpret):
    """Forward ring: rotate kv chunks via ppermute, run the Pallas flash
    kernel per chunk, merge with the online softmax. The schedule is
    branch-free (a traced branch over pallas calls trips XLA's closed_call
    lowering cache): step 0 is statically the diagonal (causal kernel);
    all later steps run the non-causal kernel unconditionally and
    causally-invisible chunks are masked out of the merge — the same
    uniform schedule the jnp ring uses. Returns the normalized local
    output and its GLOBAL per-row lse (what the backward kernels need).

    Dropout (rate > 0, real TPU only): each (owner, chunk) pair gets its
    own kernel seed via _chunk_seed, so masks are independent across ring
    steps AND devices; the per-chunk outputs are normalized by the TRUE
    (pre-dropout) softmax masses, so the merged result is exactly
    dropout(P_full) @ V — the dense semantics."""
    from solvingpapers_tpu.kernels.flash_attention import _fwd

    n_heads, n_kv = heads
    block_q, block_k = blocks
    bn, s_loc, d = q3.shape
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    m0 = jnp.full_like(q3[..., 0], BIG_NEG, dtype=jnp.float32)  # (bn, s)
    l0 = jnp.zeros_like(m0)
    acc0 = jnp.zeros_like(q3, dtype=jnp.float32)

    # step 0: every device holds its own (diagonal) chunk
    o0, lse0 = _fwd(q3, k3, v3,
                    _chunk_seed(seed, my_idx, my_idx, axis_size),
                    n_heads, n_kv, scale, causal,
                    block_q, block_k, dropout_rate, interpret)
    m, l, acc = _ring_merge(m0, l0, acc0, o0.astype(jnp.float32),
                            lse0[:, 0, :])

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (my_idx - i) % axis_size
        o_c, lse_c = _fwd(q3, k_cur, v_cur,
                          _chunk_seed(seed, my_idx, src, axis_size),
                          n_heads, n_kv, scale,
                          False, block_q, block_k, dropout_rate, interpret)
        lse_c = lse_c[:, 0, :]
        if causal:
            # chunk src = (my - i) % size is visible iff it is globally
            # earlier; invisible chunks contribute zero mass via lse
            lse_c = jnp.where(src < my_idx, lse_c, BIG_NEG)
        m, l, acc = _ring_merge(m, l, acc, o_c.astype(jnp.float32), lse_c)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    k1 = jax.lax.ppermute(k3, axis_name, perm)
    v1 = jax.lax.ppermute(v3, axis_name, perm)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m, l, acc, k1, v1), jnp.arange(1, axis_size)
    )
    # guard fully-masked rows (no visible kv anywhere) like the kernel does
    safe_l = jnp.where(l > 0.0, l, 1.0)
    out = (acc / safe_l[..., None]).astype(q3.dtype)
    lse_g = jnp.where(l > 0.0, m + jnp.log(safe_l), 0.0)[:, None, :]  # (bn,1,s)
    return out, lse_g


def _ring_flash_vjp_fwd(q3, k3, v3, seed, axis_name, heads, scale, causal,
                        blocks, dropout_rate, interpret):
    out, lse_g = _ring_flash_fwd_scan(q3, k3, v3, seed, axis_name, heads,
                                      scale, causal, blocks, dropout_rate,
                                      interpret)
    return out, (q3, k3, v3, seed, out, lse_g)


def _ring_flash_vjp_bwd(axis_name, heads, scale, causal, blocks,
                        dropout_rate, interpret, res, do):
    """Backward ring: rotate (k, v, dk, dv) together; each step runs the
    shared _bwd_chunk pallas sweeps against the resident chunk with the
    GLOBAL lse/delta, accumulating dq locally and dk/dv onto the traveling
    chunk. After a full cycle the dk/dv land back on their home device.
    With dropout, each chunk's _chunk_seed matches the forward's, so the
    backward kernels regenerate the exact forward masks."""
    from solvingpapers_tpu.kernels.flash_attention import _bwd_chunk

    q3, k3, v3, seed, out, lse_g = res
    n_heads, n_kv = heads
    group = n_heads // n_kv
    block_q, block_k = blocks
    bn, s_loc, d = q3.shape
    bkv = k3.shape[0]
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)[:, None, :]

    def rep(x):
        if group == 1:
            return x
        return jnp.repeat(
            x.reshape(bkv // n_kv, n_kv, s_loc, d), group, axis=1
        ).reshape(bn, s_loc, d)

    def fold(x):
        if group == 1:
            return x
        b = bn // n_heads
        return x.reshape(b, n_kv, group, s_loc, d).sum(axis=2).reshape(
            bkv, s_loc, d
        )

    def chunk_bwd(k_cur, v_cur, is_causal, lse_in, chunk_seed):
        dq, dk_r, dv_r = _bwd_chunk(
            q3, rep(k_cur), rep(v_cur), do, lse_in, delta, chunk_seed,
            scale=scale, causal=is_causal, block_q=block_q,
            block_k=block_k, dropout_rate=dropout_rate, interpret=interpret,
        )
        return (dq.astype(jnp.float32), fold(dk_r).astype(jnp.float32),
                fold(dv_r).astype(jnp.float32))

    # step 0: the diagonal chunk, statically causal — no masking needed
    dq_acc, dk_cur, dv_cur = chunk_bwd(
        k3, v3, causal, lse_g, _chunk_seed(seed, my_idx, my_idx, axis_size)
    )

    def step(carry, i):
        dq_acc, k_cur, v_cur, dk_cur, dv_cur = carry
        lse_in = lse_g
        src = (my_idx - i) % axis_size
        if causal:
            # invisible chunks (globally later than this q shard) must
            # contribute nothing. Mask BEFORE the kernel's exp(s - lse)
            # (push lse to +huge so p underflows to exactly 0): a post-hoc
            # grad * 0.0 would turn an exp overflow from unmasked outlier
            # scores into inf * 0 = NaN
            lse_in = jnp.where(src < my_idx, lse_g,
                               jnp.full_like(lse_g, -BIG_NEG))
        dq_c, dk_c, dv_c = chunk_bwd(
            k_cur, v_cur, False, lse_in,
            _chunk_seed(seed, my_idx, src, axis_size),
        )
        dq_acc = dq_acc + dq_c
        dk_cur = dk_cur + dk_c
        dv_cur = dv_cur + dv_c
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq_acc, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    # rotate (k, v) once so the scan sees chunks src = my-1, my-2, ...;
    # (dk, dv) ride along so each lands home after the full cycle
    k1 = jax.lax.ppermute(k3, axis_name, perm)
    v1 = jax.lax.ppermute(v3, axis_name, perm)
    dk1 = jax.lax.ppermute(dk_cur, axis_name, perm)
    dv1 = jax.lax.ppermute(dv_cur, axis_name, perm)
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq_acc, k1, v1, dk1, dv1), jnp.arange(1, axis_size)
    )
    # rotation count check: 1 pre-rotation + (size-1) end-of-step rotations
    # = size total, so every dk/dv chunk is back on its home device, with
    # the last contribution added before the final rotation
    import numpy as np

    seed_ct = np.zeros(seed.shape, jax.dtypes.float0)  # int arg: no tangent
    return (dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype),
            seed_ct)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_flash_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    dropout_rate: float = 0.0,
    dropout_seed: jax.Array | int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-chunk core
    (VERDICT r1 item 7): call inside shard_map with the sequence sharded
    over `axis_name`. Same layout contract as ring_attention_local —
    q: (B, S_loc, N, H), k/v: (B, S_loc, Nkv, H), GQA kv heads travel
    un-repeated (ppermute carries only Nkv heads; repetition happens per
    chunk inside the kernels). The (S, S) score matrix never exists on any
    device, and each chunk's inner loop is the MXU-tiled kernel instead of
    a jnp einsum."""
    from solvingpapers_tpu.kernels.flash_attention import (
        _pick_block,
        _pick_block_q,
        auto_block,
    )

    b, s_loc, n, h = q.shape
    n_kv = k.shape[2]
    if n % n_kv:
        raise ValueError(f"q heads {n} not a multiple of kv heads {n_kv}")
    if k.shape[1] != s_loc:
        # square per-shard chunks are the ring contract: the merge treats
        # the kernel's empty-row lse=0 sentinel as real unit mass, which
        # unequal shard lengths could trigger
        raise ValueError(
            f"ring chunks must be square: q shard seq {s_loc} != kv shard "
            f"seq {k.shape[1]}"
        )
    if scale is None:
        scale = h**-0.5
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    if dropout_rate > 0.0 and interpret:
        raise ValueError(
            "in-kernel dropout requires the hardware PRNG: interpret-mode "
            "pltpu.prng_random_bits is a zero stub (kernels/flash_attention)"
        )
    # seq-adaptive auto like flash_attention: an 8k+ CP shard gets the
    # long-sequence tile (the 16k sweep's 1.5-2x backward win applies to
    # each ring chunk too)
    bq = _pick_block_q(s_loc, auto_block(s_loc, block_q))
    bk = _pick_block(s_loc, auto_block(s_loc, block_k))

    q3 = q.transpose(0, 2, 1, 3).reshape(b * n, s_loc, h)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * n_kv, s_loc, h)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * n_kv, s_loc, h)
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    o3 = _ring_flash(
        q3, k3, v3, seed, axis_name, (n, n_kv), float(scale), bool(causal),
        (bq, bk), float(dropout_rate), interpret,
    )
    return o3.reshape(b, n, s_loc, h).transpose(0, 2, 1, 3)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    axis_name: str = "context",
    interpret: bool | None = None,
) -> jax.Array:
    """Full-array entry point for ring_flash_attention_local (tests/bench).

    check_vma=False: a pallas_call inside lax.scan under the jax-0.9 vma
    checker KeyErrors in the closed_call lowering cache; the computation is
    identical either way (verified against dense).
    """
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = functools.partial(
        ring_flash_attention_local, axis_name=axis_name, causal=causal,
        scale=scale, interpret=interpret,
    )
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    attn_fn,
) -> jax.Array:
    """Ulysses sequence parallelism: all_to_all swaps the sequence shard for
    a head shard around the attention core (SURVEY.md §2.3 Ulysses row).

    q, k, v: local (B, S_loc, N, H); requires N % axis_size == 0. attn_fn
    receives full-sequence (B, S, N_loc, H) tensors — any attention core
    works (dense, flash kernel).
    """
    axis_size = jax.lax.psum(1, axis_name)
    if q.shape[2] % axis_size or k.shape[2] % axis_size:
        raise ValueError(
            f"Ulysses needs q heads ({q.shape[2]}) and kv heads "
            f"({k.shape[2]}) divisible by the '{axis_name}' axis size "
            f"({axis_size})"
        )
    # split heads across devices, gather sequence: (B, S, N/axis, H)
    q_g = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k_g = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v_g = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    o_g = attn_fn(q_g, k_g, v_g)
    # swap back: scatter sequence, gather heads
    return jax.lax.all_to_all(o_g, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    attn_fn,
    *,
    axis_name: str = "context",
) -> jax.Array:
    """Full-array Ulysses entry: sequence sharded over `axis_name`, heads
    resharded around `attn_fn` via all_to_all."""
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = functools.partial(
        ulysses_attention_local, axis_name=axis_name, attn_fn=attn_fn
    )
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def cp_halo_right(
    x: jax.Array,
    k: int,
    axis_name: str = "context",
    fill=0,
):
    """The first k sequence columns (dim 1) of the RIGHT neighbor's shard —
    a k-token halo exchange over the context axis via one ppermute. The
    last shard, whose halo would wrap around to shard 0, gets `fill`
    instead (the global sequence ends there).

    This is the collective that makes MTP's i+k target shift
    (deepseekv3.ipynb cell 46) local under context parallelism: shard-local
    `concat([x[:, k:], cp_halo_right(x, k)], 1)` equals the global
    left-shift-by-k of the full sequence, zero/fill-padded at the end.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    head = jax.lax.slice_in_dim(x, 0, k, axis=1)
    # source i delivers to dest i-1: every shard receives its RIGHT
    # neighbor's head
    perm = [(i, (i - 1) % n) for i in range(n)]
    halo = jax.lax.ppermute(head, axis_name, perm)
    return jnp.where(idx == n - 1, jnp.full_like(halo, fill), halo)


def cp_shift_left(
    x: jax.Array,
    k: int,
    axis_name: str = "context",
    fill=0,
) -> jax.Array:
    """Shard-local view of the GLOBAL left-shift-by-k of the sequence
    (dim 1): local columns [k:] followed by the right neighbor's first k
    columns (cp_halo_right), `fill` past the global end. The one shared
    implementation of MTP's i+k shift under context parallelism — used by
    the dense family's shifted-embedding stream, the staged family's MTP
    branch, and the loss's target stream."""
    return jnp.concatenate(
        [x[:, k:], cp_halo_right(x, k, axis_name, fill)], axis=1
    )
