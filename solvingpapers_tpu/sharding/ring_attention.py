"""Ring attention: context parallelism over the `context` mesh axis.

Not present in the reference (max trained context is 256 tokens,
SURVEY.md §5 "Long-context — absent") — this is the capability the new
framework adds for sequences larger than one chip's HBM. Each device holds
a sequence shard of Q, K, V; K/V chunks rotate around the ring via
`lax.ppermute` over ICI while every device accumulates its queries' online
softmax (the blockwise/flash recurrence, so the full (S, S) score matrix
never exists anywhere).

Layout: BSNH shards inside shard_map. Causality is resolved from global
chunk positions (device i holds positions [i*S_loc, (i+1)*S_loc)); fully
masked chunks still traverse the ring (uniform schedule keeps the
collective static) but contribute zero mass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from solvingpapers_tpu.ops.attention import BIG_NEG, repeat_kv


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Per-shard ring attention body; call inside shard_map.

    q: local (B, S_loc, N, H) sequence shard; k, v: (B, S_loc, Nkv, H) with
    N % Nkv == 0 — GQA kv heads are repeated per ring step AFTER the
    transfer, so ppermute traffic carries only the Nkv heads. Returns the
    local (B, S_loc, N, H) output shard of exact softmax attention over the
    full sequence.
    """
    b, s_loc, n, h = q.shape
    n_kv = k.shape[2]
    if n % n_kv:
        raise ValueError(f"q heads {n} not a multiple of kv heads {n_kv}")
    group = n // n_kv
    if scale is None:
        scale = h**-0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    q32 = q.astype(jnp.float32) * scale
    q_pos = my_idx * s_loc + jnp.arange(s_loc)

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # ppermute sends to (j+1): after i steps we hold chunk (my_idx - i)
        src = (my_idx - i) % axis_size
        s_ = jnp.einsum(
            "bqnh,bknh->bnqk", q32, repeat_kv(k_cur, group).astype(jnp.float32)
        )
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            s_ = jnp.where(mask, s_, BIG_NEG)
        m_new = jnp.maximum(m, jnp.max(s_, axis=-1, keepdims=True))
        p = jnp.exp(s_ - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bnqk,bknh->bqnh", p, repeat_kv(v_cur, group).astype(jnp.float32)
        ).transpose(0, 2, 1, 3)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    # derive initial accumulators from q so they inherit its varying-axes
    # type (shard_map vma typing: plain zeros would be device-invariant)
    q_bnsh = jnp.moveaxis(q32, 1, 2)  # (B, N, S_loc, H)
    m0 = jnp.full_like(q_bnsh[..., :1], BIG_NEG)
    l0 = jnp.zeros_like(q_bnsh[..., :1])
    acc0 = jnp.zeros_like(q_bnsh)
    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(axis_size)
    )
    out = acc / jnp.maximum(l, 1e-30)  # (B, N, S_loc, H)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: float | None = None,
    axis_name: str = "context",
) -> jax.Array:
    """Full-array entry point: shards the sequence axis over `axis_name`
    (batch over data/fsdp) and runs the ring. q, k, v: (B, S, N, H) with
    S divisible by the context axis size."""
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = functools.partial(
        ring_attention_local, axis_name=axis_name, causal=causal, scale=scale
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    attn_fn,
) -> jax.Array:
    """Ulysses sequence parallelism: all_to_all swaps the sequence shard for
    a head shard around the attention core (SURVEY.md §2.3 Ulysses row).

    q, k, v: local (B, S_loc, N, H); requires N % axis_size == 0. attn_fn
    receives full-sequence (B, S, N_loc, H) tensors — any attention core
    works (dense, flash kernel).
    """
    axis_size = jax.lax.psum(1, axis_name)
    if q.shape[2] % axis_size or k.shape[2] % axis_size:
        raise ValueError(
            f"Ulysses needs q heads ({q.shape[2]}) and kv heads "
            f"({k.shape[2]}) divisible by the '{axis_name}' axis size "
            f"({axis_size})"
        )
    # split heads across devices, gather sequence: (B, S, N/axis, H)
    q_g = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k_g = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v_g = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    o_g = attn_fn(q_g, k_g, v_g)
    # swap back: scatter sequence, gather heads
    return jax.lax.all_to_all(o_g, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    attn_fn,
    *,
    axis_name: str = "context",
) -> jax.Array:
    """Full-array Ulysses entry: sequence sharded over `axis_name`, heads
    resharded around `attn_fn` via all_to_all."""
    spec = P(("data", "fsdp"), axis_name, None, None)
    fn = functools.partial(
        ulysses_attention_local, axis_name=axis_name, attn_fn=attn_fn
    )
    return jax.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
