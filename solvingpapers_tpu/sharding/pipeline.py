"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Not in the reference (SURVEY.md §2.3 lists PP as a TPU-native capability to
add; its parallelism ceiling is single-process DataParallel). Design: each
device along the `pipe` axis holds ONE stage's parameters (stacked arrays
with a leading stage dimension, sharded over the axis). Microbatches enter
at stage 0 and hop stage-to-stage via `lax.ppermute` over ICI; the schedule
runs `n_micro + n_stages - 1` ticks, every device computing each tick
(bubbles compute garbage that is masked out at collection). The classic
collective-permute pipelining recipe — compute and neighbor-transfer
overlap, no host involvement.

Capability scope: stage_fn is any pure function (params_stage, x) -> x with
matching input/output activation shapes (transformer blocks, MLP stacks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def vma_axes(x) -> frozenset:
    """Varying-manual-axes of `x` under the jax-0.9 vma checker, or an
    empty set on jax versions without `jax.typeof` (no vma tracking — and
    every pcast in the schedules is gated on a nonempty result, so the
    schedules degrade to plain SPMD semantics there)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(x), "vma", ()) or ())


def shard_map_compat(fn, mesh, in_specs, out_specs, check_vma=None):
    """jax.shard_map where it exists (passing `check_vma` when given);
    the legacy jax.experimental.shard_map with the rep checker off
    elsewhere (the legacy checker predates the vma typing the
    schedules' pcasts target, and check_rep=False matches the
    check_vma=False semantics the schedules are written for). THE
    jax-version shim for every shard_map in this repo — exported from
    `solvingpapers_tpu.sharding`; new multi-device code should route
    through it rather than calling jax.shard_map directly."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as legacy

    return legacy(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


# short internal aliases (the schedule bodies below use them heavily)
_vma = vma_axes
_shard_map = shard_map_compat


# ------------------------------------------------------ schedule algebra
#
# The tick math the schedules below implement, exposed as plain functions
# so the mesh observatory (metrics/mesh_obs.py) can label per-tick trace
# spans and compute bubble fractions without re-deriving (and drifting
# from) the schedule internals.


def schedule_ticks(n_microbatches: int, n_stages: int, n_virtual: int = 1,
                   schedule: str = "gpipe") -> int:
    """Scan length of one pipeline pass. GPipe/interleaved forward:
    m*v + P - 1 ticks; 1F1B (forward AND backward units interleaved):
    2(m + P) - 2 ticks, i.e. ~m + P - 1 full F+B unit-pairs."""
    if schedule == "1f1b":
        if n_virtual != 1:
            raise ValueError("1f1b does not compose with virtual stages")
        return 2 * (n_microbatches + n_stages) - 2
    return n_microbatches * n_virtual + n_stages - 1


def analytic_bubble_fraction(n_microbatches: int, n_stages: int,
                             n_virtual: int = 1) -> float:
    """The balanced-stage bubble fraction (P-1)/(m*v + P - 1): the share
    of a pipeline pass spent ramping/draining when every stage costs the
    same. Holds for the forward schedules tick-for-tick and for 1F1B in
    F+B unit-pairs (its steady state is bubble-free, the ramp is the
    same P-1 units)."""
    return (n_stages - 1) / (n_microbatches * n_virtual + n_stages - 1)


def tick_unit(t: int, device: int, n_microbatches: int, n_stages: int,
              n_virtual: int = 1, schedule: str = "gpipe") -> str:
    """Which unit device `device` computes at tick `t`: "F<i>" (forward,
    microbatch i), "B<i>" (1F1B backward), "F<i>.v<j>" (interleaved,
    virtual slice j), or "bubble" (ramp/drain garbage compute — this
    implementation's bubbles BURN a tick computing masked-out garbage,
    they do not idle). Mirrors the schedule bodies above exactly."""
    m, P, v = n_microbatches, n_stages, n_virtual
    if schedule == "1f1b":
        rel_f = t - device
        if rel_f >= 0 and rel_f % 2 == 0 and rel_f // 2 < m:
            return f"F{rel_f // 2}"
        rel_b = t - (2 * P - 1 - device)
        if rel_b >= 0 and rel_b % 2 == 0 and rel_b // 2 < m:
            return f"B{rel_b // 2}"
        return "bubble"
    rel = t - device
    if rel < 0 or rel >= m * v:
        return "bubble"
    if v == 1:
        return f"F{rel}"
    g = rel // (v * P)
    i = rel % P
    j = (rel % (v * P)) // P
    return f"F{g * P + i}.v{j}"


def _pipeline_local(stage_params, microbatches, stage_fn, axis_name,
                    with_aux: bool = False, rng=None):
    """Per-device body. stage_params: this stage's params (leading stage
    axis already stripped to size 1 by shard_map — squeezed here).
    microbatches: (n_micro, mb, ...) full input, replicated.

    with_aux: stage_fn returns (y, aux_pytree) and the schedule SUMS aux
    over this device's VALID ticks only (stage s computes real microbatches
    at ticks [s, s + n_micro); bubble ticks compute garbage that must not
    pollute statistics). Returns (out, aux_sum) — aux_sum covers exactly
    the full batch as seen by THIS device's stage (e.g. MoE routing loads
    for its layers); callers reduce across other mesh axes themselves.

    rng: when given, stage_fn is called as stage_fn(params, x, unit_rng)
    with unit_rng = fold_in(fold_in(rng, stage_id), microbatch_index) —
    the regenerable-seed recipe that makes DROPOUT well-defined under the
    schedule: at tick t stage s processes microbatch t - s, so the mask a
    (stage, microbatch) unit sees is a pure function of the fold chain and
    regenerates identically in the backward/remat replay (the same salting
    idea as the CP ring's per-(owner, chunk) kernel seeds).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_id = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    n_micro = microbatches.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    stage_rng = None if rng is None else jax.random.fold_in(rng, stage_id)

    # shard_map vma typing: carriers and the replicated input must be marked
    # varying over the pipe axis before mixing with per-device values — but
    # only when vma tracking is active; under check_vma=False the pcast's
    # TRANSPOSE (a psum over the axes) fails in the backward pass. Probe
    # tracking via the stage params, which enter sharded over the pipe axis
    # and therefore read as pipe-varying exactly when tracking is on.
    probe = jax.tree.leaves(stage_params)[0]
    tracking = axis_name in _vma(probe)
    if tracking and axis_name not in _vma(microbatches):
        microbatches = jax.lax.pcast(microbatches, (axis_name,), to="varying")
    buf = jnp.zeros_like(microbatches[0])  # current activation on this device
    out = jnp.zeros_like(microbatches)     # collected at the last stage

    def run_stage(params, incoming, unit_rng=None):
        if rng is None:
            res = stage_fn(params, incoming)
        else:
            res = stage_fn(params, incoming, unit_rng)
        return res if with_aux else (res, None)

    # aux structure probe (shapes only) for the scan carry init
    aux_shapes = (
        jax.eval_shape(
            lambda p, x: run_stage(p, x, stage_rng)[1], params, buf
        )
        if with_aux else None
    )

    def tick(carry, t):
        buf, out, aux_acc = carry
        # stage 0 ingests microbatch t (when in range); others use the
        # activation received from the previous stage
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        incoming = jnp.where(
            stage_id == 0,
            microbatches[mb_idx].astype(buf.dtype),
            buf,
        )
        unit_rng = None
        if rng is not None:
            # the microbatch THIS stage processes at tick t is t - stage_id
            # (bubble ticks clip to a valid index; their output is garbage
            # and masked at collection regardless)
            mb_cur = jnp.clip(t - stage_id, 0, n_micro - 1)
            unit_rng = jax.random.fold_in(stage_rng, mb_cur)
        y, aux = run_stage(params, incoming, unit_rng)
        if with_aux:
            # stage s holds real data at ticks [s, s + n_micro)
            valid = (t >= stage_id) & (t < stage_id + n_micro)
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(valid, a, 0.0).astype(acc.dtype),
                aux_acc, aux,
            )
        # the microbatch finishing at the last stage this tick is t-(S-1)
        done_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_valid = (stage_id == n_stages - 1) & (t >= n_stages - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            out, y.astype(out.dtype), done_idx, 0
        )
        out = jnp.where(is_valid, updated, out)
        # rotate activations one stage forward (last->0 wraps; ignored)
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, out, aux_acc), None

    def zero_like_shape(s):
        # the scan carry's vma type must match what run_stage produces
        # (varying over the pipe axis via stage params, and over the data
        # axes via the batch) — eval_shape carries the vma when tracking
        z = jnp.zeros(s.shape, jnp.float32)
        vma = tuple(getattr(s, "vma", ()) or ())
        return jax.lax.pcast(z, vma, to="varying") if vma else z

    aux0 = jax.tree.map(zero_like_shape, aux_shapes) if with_aux else None
    (_, out, aux_sum), _ = jax.lax.scan(
        tick, (buf, out, aux0), jnp.arange(ticks)
    )
    # only the last stage holds real outputs; psum broadcasts them (others zero)
    out = jnp.where(stage_id == n_stages - 1, out, jnp.zeros_like(out))
    out = jax.lax.psum(out, axis_name)
    return (out, aux_sum) if with_aux else out


def _pipeline_local_interleaved(stage_params, microbatches, stage_fn,
                                axis_name, n_virtual, rng=None,
                                with_aux: bool = False):
    """Interleaved (virtual-stage) schedule: device d holds `n_virtual`
    THIN stages (global stage j*P + d stored at local row j), microbatches
    enter in groups of P and loop the ring v times consecutively — the
    Megatron-style bubble shrink, forward-only form. Ticks = m*v + P - 1
    with every device busy except the P-1 ramp ticks, so the bubble
    fraction is (P-1)/(m*v + P - 1) — v times smaller than GPipe's at
    equal microbatch count (each tick does 1/v of a GPipe stage's work).

    Schedule algebra (conflict-free by construction): group g member i
    enters device 0 at tick g*v*P + i; after s total hops it sits on
    device s mod P running virtual slice s // P, i.e. device d at tick
    t holds the unit with (t - d) >= 0, g = (t-d) // (v*P),
    i = (t-d) % P, slice j = ((t-d) % (v*P)) // P. Device 0's ingest
    ticks (t % (v*P) < P) never collide with wrapped units, and group
    g+1's ingest lands exactly as group g's last loop leaves.

    stage_fn(stage_params_slice_j, x[, rng][, virtual_idx]) -> y (or
    (y, aux) with `with_aux`); requires n_micro % P == 0.

    with_aux: aux is accumulated into a leading (n_virtual,) stack — row j
    sums virtual slice j's n_micro VALID ticks (device d's row j covers
    global stage j*P + d; bubble ticks are masked out). Each (global
    stage, microbatch) unit runs exactly once across all valid ticks, so
    the stacked sums have the same per-stage coverage as the GPipe
    schedule's aux (callers scatter rows j -> storage row d*v + j).
    """
    n_stages = jax.lax.psum(1, axis_name)  # P devices
    d_id = jax.lax.axis_index(axis_name)
    params_v = stage_params  # already this device's (v, ...) local rows
    n_micro = microbatches.shape[0]
    vP = n_virtual * n_stages
    ticks = n_micro * n_virtual + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    probe = jax.tree.leaves(stage_params)[0]
    tracking = axis_name in _vma(probe)
    if tracking and axis_name not in _vma(microbatches):
        microbatches = jax.lax.pcast(microbatches, (axis_name,), to="varying")
    buf = jnp.zeros_like(microbatches[0])
    out = jnp.zeros_like(microbatches)

    def run_virtual(j, incoming, unit_rng):
        res = _apply_virtual(params_v, j, incoming, stage_fn, n_virtual,
                             unit_rng, rng_used=rng is not None)
        return res if with_aux else (res, None)

    aux_shapes = (
        jax.eval_shape(
            lambda p, x: run_virtual(jnp.zeros((), jnp.int32), x, rng)[1],
            params_v, buf,
        )
        if with_aux else None
    )

    def tick(carry, t):
        buf, out, aux_acc = carry
        rel = t - d_id  # hops since this device's current unit entered
        g = jnp.maximum(rel, 0) // vP
        i = jnp.maximum(rel, 0) % n_stages
        j = (jnp.maximum(rel, 0) % vP) // n_stages  # virtual slice index
        # device 0 ingests a NEW microbatch whenever its unit is at hop 0
        ingest = (d_id == 0) & (t % vP < n_stages)
        mb_idx = jnp.clip(g * n_stages + i, 0, n_micro - 1)
        incoming = jnp.where(
            ingest, microbatches[mb_idx].astype(buf.dtype), buf
        )
        unit_rng = None
        if rng is not None:
            # global stage of virtual slice j on device d is j*P + d;
            # fold (global stage, microbatch) exactly like _pipeline_local
            unit_rng = jax.random.fold_in(
                jax.random.fold_in(rng, j * n_stages + d_id), mb_idx
            )
        y, aux = run_virtual(j, incoming, unit_rng)
        if with_aux:
            # this device's unit is real for the first m*v ticks after its
            # ramp (rel in [0, m*v)) — every (slice, microbatch) pair once
            valid = (rel >= 0) & (rel < n_micro * n_virtual)

            def acc_row(acc, a):
                row = jax.lax.dynamic_index_in_dim(acc, j, 0, keepdims=False)
                row = row + jnp.where(valid, a, 0.0).astype(acc.dtype)
                return jax.lax.dynamic_update_index_in_dim(acc, row, j, 0)

            aux_acc = jax.tree.map(acc_row, aux_acc, aux)
        # unit completes at device P-1 on its last slice
        done = (
            (d_id == n_stages - 1)
            & (rel >= 0)
            & (rel % vP >= (n_virtual - 1) * n_stages)
            & (g * n_stages + i < n_micro)
        )
        updated = jax.lax.dynamic_update_index_in_dim(
            out, y.astype(out.dtype), mb_idx, 0
        )
        out = jnp.where(done, updated, out)
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, out, aux_acc), None

    def zero_stack_shape(s):
        # (n_virtual, *aux shape) accumulator matching run_virtual's vma
        z = jnp.zeros((n_virtual, *s.shape), jnp.float32)
        vma = tuple(getattr(s, "vma", ()) or ())
        return jax.lax.pcast(z, vma, to="varying") if vma else z

    aux0 = jax.tree.map(zero_stack_shape, aux_shapes) if with_aux else None
    (_, out, aux_sum), _ = jax.lax.scan(
        tick, (buf, out, aux0), jnp.arange(ticks)
    )
    out = jnp.where(d_id == n_stages - 1, out, jnp.zeros_like(out))
    out = jax.lax.psum(out, axis_name)
    return (out, aux_sum) if with_aux else out


def _apply_virtual(params_v, j, x, stage_fn, n_virtual, unit_rng=None,
                   rng_used=None):
    """Run stage_fn with this device's virtual-slice-j params. j is traced,
    so slice with lax.switch over the (python-static) v rows — a dynamic
    gather of a whole param subtree would copy it; switch lets XLA keep
    each branch's weights in place. Each branch passes its python-static
    slice index as `virtual_idx` so stage_fns that need the GLOBAL stage id
    (j*P + d — e.g. the flagship's routing-bias slicing) can derive it.
    `rng_used` distinguishes 'no rng this call' (None key) from 'schedule
    has no rng arg at all' (2-arg stage_fn); default: keyed iff unit_rng."""
    if rng_used is None:
        rng_used = unit_rng is not None
    if not rng_used:
        branches = [
            lambda x, jj=jj: stage_fn(
                jax.tree.map(lambda a: a[jj], params_v), x, virtual_idx=jj
            )
            for jj in range(n_virtual)
        ]
        return jax.lax.switch(j, branches, x)
    branches = [
        lambda x, r, jj=jj: stage_fn(
            jax.tree.map(lambda a: a[jj], params_v), x, r, virtual_idx=jj
        )
        for jj in range(n_virtual)
    ]
    return jax.lax.switch(j, branches, x, unit_rng)


def pipeline_local_apply(
    stage_params,
    x: jax.Array,
    stage_fn,
    *,
    n_microbatches: int,
    axis_name: str = "pipe",
    with_aux: bool = False,
    rng=None,
):
    """Per-device GPipe entry for callers already inside shard_map (e.g. a
    pipeline-parallel model's forward): splits x (batch, ...) into
    microbatches, runs the schedule, and restores the batch shape.
    stage_params is this device's stage slice (leading stage dim 1).
    With `with_aux`, stage_fn returns (y, aux) and this returns
    (out, aux_summed_over_valid_ticks) — see _pipeline_local.
    With `rng`, stage_fn is called as (params, x, unit_rng) — per-(stage,
    microbatch) dropout keys (see _pipeline_local)."""
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches} microbatches")
    micro = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])
    res = _pipeline_local(stage_params, micro, stage_fn, axis_name,
                          with_aux=with_aux, rng=rng)
    if with_aux:
        out, aux = res
        return out.reshape(b, *x.shape[1:]), aux
    return res.reshape(b, *x.shape[1:])


def pipeline_local_apply_interleaved(
    stage_params,
    x: jax.Array,
    stage_fn,
    *,
    n_microbatches: int,
    n_virtual: int,
    axis_name: str = "pipe",
    rng=None,
    with_aux: bool = False,
):
    """Per-device interleaved-schedule entry (see
    _pipeline_local_interleaved). stage_params: this device's (v, ...)
    virtual-slice rows. Does not compose with collectives inside stage_fn
    (slice selection is a data-dependent branch), so CP x interleaved is
    rejected at the model layer. With `rng`, stage_fn is called as
    (params, x, unit_rng, virtual_idx=j) keyed by (global stage,
    microbatch). With `with_aux`, stage_fn returns (y, aux) and this
    returns (out, aux stacked per virtual slice)."""
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches} microbatches")
    micro = x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])
    res = _pipeline_local_interleaved(
        stage_params, micro, stage_fn, axis_name, n_virtual, rng=rng,
        with_aux=with_aux,
    )
    if with_aux:
        out, aux = res
        return out.reshape(b, *x.shape[1:]), aux
    return res.reshape(b, *x.shape[1:])


def pipeline_1f1b_value_and_grad(
    stage_params,
    head_params,
    microbatches: jax.Array,
    targets: jax.Array,
    stage_fn,
    loss_fn,
    axis_name: str = "pipe",
    rng=None,
    with_aux: bool = False,
):
    """One-forward-one-backward schedule (SURVEY.md §2.3 PP row): loss AND
    gradients in a single pass whose live activation memory is bounded by
    the PIPE DEPTH, not the microbatch count.

    With `rng`, stage_fn is called as (params, x, unit_rng) with
    unit_rng = fold_in(fold_in(rng, stage_id), microbatch) — the same
    regenerable-key recipe as the GPipe schedule, and because the
    backward unit derives the IDENTICAL key before its recompute-vjp,
    dropout masks regenerate exactly and the grads are the true grads of
    the masked forward.

    With `with_aux`, stage_fn returns (y, aux_pytree) and the schedule
    SUMS aux over this device's valid FORWARD units only (each (stage,
    microbatch) counted once; the backward recompute's aux is discarded)
    — the same per-stage coverage as `_pipeline_local`'s aux channel, for
    the flagship's MoE routing loads. An extra aux_sum is appended to the
    return tuple.

    GPipe (jax.grad over `_pipeline_local`'s scan) must stash every tick's
    residuals — activation memory grows with n_micro, which is exactly what
    `pp_grad_groups` works around by paying one fill+drain bubble per
    group. 1F1B instead schedules each microbatch's backward as soon as its
    loss exists: stage s runs forward i at tick s + 2i and backward i at
    tick 2P - 1 - s + 2i (the classic schedule in tick-synchronous SPMD
    form — F and B strictly alternate per device, so each device holds at
    most P stashed INPUTS and nothing else; the backward recomputes its
    stage forward from the stashed input, the same recompute GPipe-remat
    pays). Ticks total 2(m + P) - 3; the steady state is bubble-free.

    Per tick, uniformly on every device: one `lax.cond` (forward unit OR
    backward unit — dynamic branch, collective-free inside) then two
    ppermutes (activations downstream, cotangents upstream). The backward
    unit takes one vjp of

        where(is_last_stage, loss_fn(head, y, target), vdot(y, cot_in))

    so the LAST stage seeds the chain from its per-microbatch loss while
    the others pull the incoming cotangent through — and grads w.r.t.
    `head_params` are exactly zero on non-last stages (where-masked), so
    the pipe-psum recovers the true head gradient.

    Args: stage_params — this device's stage slice, leading dim 1 (same
    contract as `_pipeline_local`); head_params — the replicated loss head
    (e.g. final norm + lm head), threaded to `loss_fn`; microbatches
    (m, mb, ...) replicated inputs; targets (m, mb, ...) replicated;
    stage_fn(params, x) -> y shape-preserving; loss_fn(head_params, y,
    target) -> scalar MEAN loss of one microbatch (note: evaluated on
    every stage's backward unit and where-masked, so keep the head small
    relative to a stage — true for norm+vocab heads vs transformer
    stages at scale, and the price of a uniform SPMD program).

    Returns (loss, dstage_params, dhead_params, dmicrobatches): loss is
    the mean over microbatches; dstage_params has the input's leading-1
    stage dim (this device's stage); dhead_params is psum'd over the pipe
    (replicated, ready for the optimizer); dmicrobatches (m, mb, ...) is
    the cotangent w.r.t. `microbatches` (backprop it into the embedding
    outside), psum-broadcast from stage 0.

    Equality vs jax.grad over the sequential stage loop is pinned by
    tests/test_pipeline.py::test_1f1b_matches_sequential_grads.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_id = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    n_micro, mb = microbatches.shape[0], microbatches.shape[1:]
    # last backward is stage 0's B(0, m-1) at tick 2(m + P) - 3 inclusive
    ticks = 2 * (n_micro + n_stages) - 2
    down = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    up = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    is_last = stage_id == n_stages - 1

    probe = jax.tree.leaves(stage_params)[0]
    tracking = axis_name in _vma(probe)
    # the schedule's carries must be varying over the pipe axis AND over
    # whatever batch axes the inputs already vary over (under the Trainer
    # the microbatches enter data-sharded), or the cond branches/scan
    # carry would type-mismatch under the vma checker
    _target_vma = {axis_name}
    for _x in (microbatches, targets, *jax.tree.leaves(head_params)):
        _target_vma |= set(_vma(_x))

    def mark(x):
        if not tracking:
            return x
        missing = tuple(_target_vma - set(_vma(x)))
        return jax.lax.pcast(x, missing, to="varying") if missing else x

    microbatches = mark(microbatches)
    targets = mark(targets)
    head_params = jax.tree.map(mark, head_params)

    f32 = jnp.float32
    # the whole carry is inherently per-device data — mark it varying up
    # front so the two cond branches (and the scan) type-match under vma
    fwd_buf = mark(jnp.zeros(mb, f32))       # activation arriving from s-1
    bwd_buf = mark(jnp.zeros(mb, f32))       # cotangent arriving from s+1
    stash = mark(jnp.zeros((n_stages, *mb), f32))  # in-flight unit inputs
    dparams = jax.tree.map(lambda a: mark(jnp.zeros(a.shape, f32)), params)
    dhead = jax.tree.map(
        lambda a: mark(jnp.zeros(a.shape, f32)), head_params
    )
    dmicro = mark(jnp.zeros((n_micro, *mb), f32))
    loss_acc = mark(jnp.zeros((), f32))

    stage_rng = None if rng is None else jax.random.fold_in(rng, stage_id)

    def call_stage(p, x, mb_idx):
        if rng is None:
            res = stage_fn(p, x)
        else:
            res = stage_fn(p, x, jax.random.fold_in(stage_rng, mb_idx))
        return res if with_aux else (res, None)

    aux_shapes = (
        jax.eval_shape(
            lambda p, x: call_stage(p, x, jnp.zeros((), jnp.int32))[1],
            params, mark(jnp.zeros(mb, f32)).astype(probe.dtype),
        )
        if with_aux else None
    )
    aux0 = (
        jax.tree.map(
            lambda sh: mark(jnp.zeros(sh.shape, f32)), aux_shapes
        )
        if with_aux else None
    )

    def tick(carry, t):
        (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro, loss_acc,
         aux_acc) = carry
        rel_f = t - stage_id
        i_f = rel_f // 2
        do_f = (rel_f >= 0) & (rel_f % 2 == 0) & (i_f < n_micro)
        rel_b = t - (2 * n_stages - 1 - stage_id)
        i_b = rel_b // 2
        do_b = (rel_b >= 0) & (rel_b % 2 == 0) & (i_b < n_micro)

        i_f_c = jnp.clip(i_f, 0, n_micro - 1)
        i_b_c = jnp.clip(i_b, 0, n_micro - 1)

        def fwd_unit(op):
            (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro, loss_acc,
             aux_acc) = op
            x_in = jnp.where(
                stage_id == 0, microbatches[i_f_c].astype(f32), fwd_buf
            )
            # idle (ramp) ticks also land here with a clipped index — they
            # must NOT clobber a live slot another microbatch's backward
            # still needs
            stash = jnp.where(
                do_f,
                jax.lax.dynamic_update_index_in_dim(
                    stash, x_in, i_f_c % n_stages, 0
                ),
                stash,
            )
            y, aux = call_stage(params, x_in.astype(probe.dtype), i_f_c)
            y = y.astype(f32)
            if with_aux:
                # each real (stage, microbatch) forward counted once;
                # idle-tick garbage masked out
                aux_acc = jax.tree.map(
                    lambda acc, a: acc + jnp.where(do_f, a, 0.0).astype(
                        acc.dtype
                    ),
                    aux_acc, aux,
                )
            return jax.tree.map(mark, (
                y, jnp.zeros(mb, f32), stash, dparams, dhead, dmicro,
                loss_acc, aux_acc,
            ))

        def bwd_unit(op):
            (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro, loss_acc,
             aux_acc) = op
            x_in = jax.lax.dynamic_index_in_dim(
                stash, i_b_c % n_stages, 0, keepdims=False
            )
            target = targets[i_b_c]

            def unit_scalar(p, hp, x, cot, target):
                # same key as the forward unit -> identical dropout masks
                # in the recompute, so the vjp is exact; the recompute's
                # aux is discarded (already counted at the forward unit)
                y, _ = call_stage(p, x.astype(probe.dtype), i_b_c)
                y = y.astype(f32)
                per_mb = loss_fn(hp, y, target)
                pulled = jnp.vdot(y, cot)
                return jnp.where(is_last, per_mb, pulled), (y, per_mb)

            primal, vjp, (_, per_mb) = jax.vjp(
                unit_scalar, params, head_params, x_in, bwd_buf, target,
                has_aux=True,
            )
            # the cotangent's varying-axes type must match the primal's
            ct = jnp.ones((), f32)
            vma = tuple(_vma(primal))
            if vma:
                ct = jax.lax.pcast(ct, vma, to="varying")
            dp, dh, dx, _, _ = vjp(ct)
            dparams = jax.tree.map(lambda a, b: a + b.astype(f32),
                                   dparams, dp)
            dhead = jax.tree.map(lambda a, b: a + b.astype(f32), dhead, dh)
            # stage 0's dx is the microbatch-input cotangent
            dmicro = jnp.where(
                stage_id == 0,
                jax.lax.dynamic_update_index_in_dim(dmicro, dx, i_b_c, 0),
                dmicro,
            )
            loss_acc = loss_acc + jnp.where(is_last, per_mb, 0.0)
            return jax.tree.map(mark, (
                jnp.zeros(mb, f32), dx, stash, dparams, dhead, dmicro,
                loss_acc, aux_acc,
            ))

        # F and B ticks strictly alternate per device, so exactly one (or
        # neither, in the ramp) runs; idle ticks take the fwd branch with a
        # clipped index and the result is never consumed
        res = jax.lax.cond(do_b, bwd_unit, fwd_unit,
                           (fwd_buf, bwd_buf, stash, dparams, dhead,
                            dmicro, loss_acc, aux_acc))
        (y_send, cot_send, stash, dparams, dhead, dmicro, loss_acc,
         aux_acc) = res
        y_send = jnp.where(do_f, y_send, jnp.zeros(mb, f32))
        cot_send = jnp.where(do_b, cot_send, jnp.zeros(mb, f32))
        fwd_buf = jax.lax.ppermute(y_send, axis_name, down)
        bwd_buf_new = jax.lax.ppermute(cot_send, axis_name, up)
        # a device KEEPS its pending cotangent until its B tick consumes
        # it: the sender's B tick is exactly 1 before ours, so overwrite
        # only when fresh data arrived (sender did B at tick t)
        sender_did_b = ((t - (2 * n_stages - 2 - stage_id)) >= 0) & (
            ((t - (2 * n_stages - 2 - stage_id)) % 2 == 0)
        )
        bwd_buf = jnp.where(sender_did_b, bwd_buf_new, bwd_buf)
        return (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro,
                loss_acc, aux_acc), None

    carry0 = (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro, loss_acc,
              aux0 if with_aux else mark(jnp.zeros(())))
    (fwd_buf, bwd_buf, stash, dparams, dhead, dmicro, loss_acc,
     aux_sum), _ = jax.lax.scan(tick, carry0, jnp.arange(ticks))
    loss = jax.lax.psum(
        jnp.where(is_last, loss_acc, 0.0), axis_name
    ) / n_micro
    dhead = jax.lax.psum(jax.tree.map(lambda a: a / n_micro, dhead),
                         axis_name)
    dmicro = jax.lax.psum(
        jnp.where(stage_id == 0, dmicro, jnp.zeros_like(dmicro)), axis_name
    ) / n_micro
    dstage = jax.tree.map(lambda a: (a / n_micro)[None], dparams)
    if with_aux:
        return loss, dstage, dhead, dmicro, aux_sum
    return loss, dstage, dhead, dmicro


def pipeline_apply(
    stage_params,
    x: jax.Array,
    stage_fn,
    mesh: Mesh,
    *,
    n_microbatches: int,
    axis_name: str = "pipe",
) -> jax.Array:
    """Run x (batch, ...) through n_stages sequential stages, pipelined.

    stage_params: pytree of stacked arrays with leading dim n_stages
    (sharded over `axis_name`). stage_fn(params_one_stage, x_mb) -> y_mb
    must preserve the activation shape. Batch must divide n_microbatches.
    Semantics: stage_{S-1}(...stage_1(stage_0(x))...) — verified against the
    sequential loop in tests/test_pipeline.py.
    """
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches} microbatches")
    mb = b // n_microbatches
    micro = x.reshape(n_microbatches, mb, *x.shape[1:])

    params_spec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = functools.partial(
        _pipeline_local, stage_fn=stage_fn, axis_name=axis_name
    )
    out = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
    )(stage_params, micro)
    return out.reshape(b, *x.shape[1:])


def stack_stage_params(per_stage_params: list) -> object:
    """[stage0_params, stage1_params, ...] -> stacked pytree with a leading
    stage axis (shard over the pipe axis)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
