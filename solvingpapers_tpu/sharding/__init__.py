"""Mesh construction and sharding rules (L8).

The reference's parallelism ceiling is one `nn.DataParallel` wrap over two
GPUs (deepseekv3/deepseekv3.ipynb cells 37, 54). Here parallelism is
expressed the TPU-native way: a `jax.sharding.Mesh` with standardized axes
('data', 'fsdp', 'model', 'expert', 'context'), PartitionSpec rules over
parameter pytrees, XLA/GSPMD inserting the collectives over ICI/DCN, and
shard_map + explicit collectives for ring attention / Ulysses context
parallelism.
"""

from solvingpapers_tpu.sharding.mesh import (
    MESH_AXES,
    MeshConfig,
    ambient_mesh,
    create_mesh,
    batch_spec,
    batch_sharding,
    get_ambient_mesh,
    mesh_axis_sizes,
)
from solvingpapers_tpu.sharding.rules import (
    GPT_RULES,
    LM_RULES,
    PP_RULES,
    param_specs,
    param_shardings,
)
from solvingpapers_tpu.sharding.ring_attention import (
    cp_halo_right,
    cp_shift_left,
    ring_attention,
    ring_attention_local,
    ulysses_attention,
    ulysses_attention_local,
)
from solvingpapers_tpu.sharding.pipeline import (
    analytic_bubble_fraction,
    pipeline_apply,
    schedule_ticks,
    shard_map_compat,
    stack_stage_params,
    tick_unit,
    vma_axes,
)
from solvingpapers_tpu.sharding.distributed import (
    initialize as initialize_distributed,
    host_batch_slice,
    host_seed,
)
