"""Mesh construction and sharding rules (L8).

The reference's parallelism ceiling is one `nn.DataParallel` wrap over two
GPUs (deepseekv3/deepseekv3.ipynb cells 37, 54). Here parallelism is
expressed the TPU-native way: a `jax.sharding.Mesh` with standardized axes
('data', 'fsdp', 'model', 'expert'), PartitionSpec rules over parameter
pytrees, and XLA/GSPMD inserting the collectives over ICI/DCN.
"""

from solvingpapers_tpu.sharding.mesh import (
    MESH_AXES,
    MeshConfig,
    create_mesh,
    batch_spec,
    batch_sharding,
)
from solvingpapers_tpu.sharding.rules import (
    GPT_RULES,
    LM_RULES,
    param_specs,
    param_shardings,
)
