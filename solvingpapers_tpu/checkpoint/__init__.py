"""Checkpointing (L7): Orbax manager + params-only export."""

from solvingpapers_tpu.checkpoint.manager import CheckpointManager, export_params, load_params
