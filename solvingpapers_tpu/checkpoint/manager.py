"""One checkpoint system replacing the reference's three ad-hoc ones
(pickle pytrees — llama3 cell 12; state_dict snapshots — gemma cell 18;
{step, model, optimizer, loss} dicts with resume — deepseekv3 cell 50).

Capabilities preserved: periodic + final cadence, full-state resume
(params + optimizer + step), params-only export for weight publishing,
load-for-inference. Backed by Orbax (sharded-array aware, async-capable);
`keep_n` retention and restore-latest-at-startup give the preemption
recovery workflow the reference performs by hand.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    def __init__(self, directory: str, keep_n: int = 3, save_every: int = 1000,
                 async_saves: bool = True):
        """`async_saves`: periodic saves return as soon as the on-device
        state is snapshotted and serialize to disk in a background thread
        (SURVEY.md §5 "Orbax async checkpointing" — the step loop keeps
        running instead of stalling for the full write). Forced saves
        (final / preemption) always block until durable."""
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_every = save_every
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_n, create=True,
                enable_async_checkpointing=async_saves,
            ),
        )

    def maybe_save(self, step: int, state: Any, force: bool = False) -> bool:
        if not force and (self.save_every <= 0 or step % self.save_every):
            return False
        if force:
            # settle in-flight async saves so the dedupe check below sees
            # them, then block until this save is durable
            self._mgr.wait_until_finished()
        if step in self._mgr.all_steps():
            return False  # already saved (e.g. preemption save after periodic)
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if force:
            self._mgr.wait_until_finished()
        return True

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, abstract_state: Any) -> tuple[Any, int] | None:
        """Restore the newest checkpoint, or None if the directory is empty.

        `abstract_state` is a pytree of jax.ShapeDtypeStruct (or a concrete
        state of the right structure/sharding) used as the restore template.
        """
        step = self._mgr.latest_step()
        if step is None:
            return None
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        except Exception as e:
            if "rng" in str(e) or "(2,)" in str(e) or "(4,)" in str(e):
                raise RuntimeError(
                    f"checkpoint restore failed at step {step} — if the shape "
                    "mismatch involves 'rng', the checkpoint was written under "
                    "a different PRNG impl; set TrainConfig.prng_impl to match "
                    "('rbg' stores (4,) uint32 key data, threefry (2,))"
                ) from e
            raise
        return restored, step

    def close(self) -> None:
        self._mgr.close()


def export_params(path: str, params: Any) -> None:
    """Params-only export (the reference publishes bare weights to HF)."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()


def load_params(path: str, abstract_params: Any | None = None) -> Any:
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path), abstract_params)
