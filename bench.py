"""Benchmark of record — prints ONE JSON line.

Primary metric (top-level keys, driver contract): the reference's own GPT
char-LM training config (gpt/gpt-jax.ipynb cell 8: batch 128 x block 256 =
32,768 tok/step, dim 256, 1 head, 8 layers) trained with AdamW in bf16 on
this repo's engine, vs the reference's measured ~16.1k tok/s (1x T4,
BASELINE.md). Metric: steady-state training tokens/sec.

`scorecard` (same JSON line): the full driver-visible surface the round-2
verdict asked for (missing item 5) — the 350M MFU study point, flash-MLA
16k step time, cached-decode throughput incl. a 16k-prompt prefill row,
and (on real TPU) the in-kernel dropout linearity identity, so the
kernel's riskiest path is verified every round. Each row is isolated: a
failure records {"error": ...} instead of killing the bench.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S = 16_100.0  # gpt-jax.ipynb cell 18 tqdm, 1x T4


def _fence(x) -> float:
    # device_get of a dependent scalar: block_until_ready is not a real
    # fence on the axon-tunnelled platform
    return float(jax.device_get(x))


def _timed_windows(step, n_steps=40, n_windows=3, warmup=20):
    """Best-of-N windows of `n_steps` steps; step() must return a scalar-
    fence-able value. The tunnelled device has bursty transport noise, so
    the minimum is the honest steady-state figure."""
    for _ in range(warmup):
        out = step()
    _fence(out)
    windows = []
    for _ in range(n_windows):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            out = step()
        _fence(out)
        windows.append(time.perf_counter() - t0)
    return min(windows) / n_steps, sum(windows) / (n_windows * n_steps)


def bench_gpt_train():
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend
    from solvingpapers_tpu.metrics.mfu import (
        chip_peak_flops, transformer_flops_per_token,
    )
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    # the framework's fast path: Pallas flash attention with in-kernel
    # dropout (same Bernoulli semantics as the reference's prob dropout).
    # Off-TPU smoke runs use the dense path.
    cfg = GPTConfig(
        vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
        dropout=0.1, dtype="bfloat16", use_flash=is_tpu_backend(),
    )
    batch = 128
    tcfg = TrainConfig(
        steps=0, batch_size=batch, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=1e-3, total_steps=1000),
    )
    trainer = Trainer(GPT(cfg), tcfg)
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, size=1_000_000)
    it = lm_batch_iterator(toks, batch, cfg.block_size, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    holder = {"state": state}

    def step():
        holder["state"], metrics = trainer._train_step(
            holder["state"], next(it)
        )
        return metrics["train_loss"]

    dt, dt_mean = _timed_windows(step)
    tok_s = batch * cfg.block_size / dt
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    fpt = transformer_flops_per_token(
        n_params, cfg.n_layers, cfg.dim, cfg.block_size
    )
    return {
        "tokens_per_sec": round(tok_s, 1),
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
        "step_time_ms": round(1000 * dt, 2),
        "step_time_ms_mean": round(1000 * dt_mean, 2),
        "mfu": round(tok_s * fpt / chip_peak_flops(), 4),
        "n_params": int(n_params),
    }


def bench_350m_mfu():
    """The 342M llama3 single-chip MFU point (tools/scale_350m.py row):
    dim 1024, 24 layers, 16q/8kv heads, seq 1024, bf16, flash."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend
    from solvingpapers_tpu.metrics.mfu import (
        chip_peak_flops, transformer_flops_per_token,
    )
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    bs, seq = 8, 1024
    cfg = LlamaConfig(
        vocab_size=32_000, max_seq_len=seq, dim=1024, n_layers=24,
        n_heads=16, n_kv_heads=8, dropout=0.0, dtype="bfloat16",
        use_flash=is_tpu_backend(),
    )
    tcfg = TrainConfig(
        steps=0, batch_size=bs, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=3e-4, total_steps=100),
    )
    trainer = Trainer(Llama(cfg), tcfg)
    toks = np.random.default_rng(1).integers(0, cfg.vocab_size, size=500_000)
    it = lm_batch_iterator(toks, bs, seq, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    holder = {"state": state}

    def step():
        holder["state"], metrics = trainer._train_step(
            holder["state"], next(it)
        )
        return metrics["train_loss"]

    dt, _ = _timed_windows(step, n_steps=10, n_windows=3, warmup=8)
    tok_s = bs * seq / dt
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    fpt = transformer_flops_per_token(n_params, cfg.n_layers, cfg.dim, seq)
    return {
        "tokens_per_sec": round(tok_s, 1),
        "step_time_ms": round(1000 * dt, 2),
        "mfu": round(tok_s * fpt / chip_peak_flops(), 4),
        "n_params": int(n_params),
    }


def bench_flash_mla_16k():
    """dsv3_long's core claim: a 16,384-token flagship train step on one
    chip via flash-MLA + remat (the dense path cannot even compile)."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    seq = 16_384
    cfg = DeepSeekV3Config(
        vocab_size=32_000, block_size=seq, dtype="bfloat16", use_flash=True,
        remat=True, pe_scale=0.02, rope_dim=64, dropout=0.0, attn_dropout=0.0,
    )
    tcfg = TrainConfig(
        steps=0, batch_size=1, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=3e-4, total_steps=100),
    )
    trainer = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                      init_fn=dsv3_init_fn)
    toks = np.random.default_rng(2).integers(0, cfg.vocab_size, size=200_000)
    it = lm_batch_iterator(toks, 1, seq, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    holder = {"state": state}

    def step():
        holder["state"], metrics = trainer._train_step(
            holder["state"], next(it)
        )
        return metrics["train_loss"]

    dt, _ = _timed_windows(step, n_steps=5, n_windows=2, warmup=3)
    return {
        "seq": seq,
        "step_time_ms": round(1000 * dt, 2),
        "tokens_per_sec": round(seq / dt, 1),
    }


def bench_decode():
    """Cached scan decode (llama3 d1024 L24) — the reference re-runs the
    full forward per token (SURVEY.md §3.4)."""
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.infer import generate
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    bs, prompt_len, new = 8, 128, 256
    cfg = LlamaConfig(
        vocab_size=32_000, dim=1024, n_layers=24, n_heads=16, n_kv_heads=8,
        max_seq_len=prompt_len + new, dropout=0.0, dtype="bfloat16",
    )
    model = Llama(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (bs, prompt_len)),
        jnp.int32,
    )
    params = model.init({"params": jax.random.key(0)}, prompt)["params"]
    rng = jax.random.key(1)

    def run():
        return generate(model, params, prompt, rng, max_new_tokens=new,
                        sampler=ops.sample_greedy)

    _fence(jnp.sum(run()[:, -1]))  # compile
    best = min(
        (lambda t0: (_fence(jnp.sum(run()[:, -1])), time.perf_counter() - t0)[1])(
            time.perf_counter()
        )
        for _ in range(3)
    )
    return {
        "bs": bs, "prompt": prompt_len, "new": new,
        "tokens_per_sec": round(bs * new / best),
        "ms_per_token": round(best / new * 1e3, 3),
    }


def bench_decode_16k_prefill():
    """Long-context generation: 16k-token prompt prefill through the
    end-aligned flash path into the MLA latent cache, then scan decode."""
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.infer import generate
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

    prompt_len, new = 16_384, 32
    cfg = DeepSeekV3Config(
        vocab_size=32_000, block_size=prompt_len + new, dtype="bfloat16",
        use_flash=True, pe_scale=0.02, rope_dim=64, dropout=0.0,
        attn_dropout=0.0,
    )
    model = DeepSeekV3(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (1, prompt_len)),
        jnp.int32,
    )
    variables = model.init({"params": jax.random.key(2)},
                           jnp.zeros((1, 8), jnp.int32))
    extra = {"moe_state": variables["moe_state"]}
    rng = jax.random.key(3)

    def run(n):
        return generate(model, variables["params"], prompt, rng,
                        max_new_tokens=n, sampler=ops.sample_greedy,
                        extra_variables=extra, prefill_chunk=2048)

    _fence(jnp.sum(run(1)[:, -1]))  # compile prefill
    t0 = time.perf_counter()
    _fence(jnp.sum(run(1)[:, -1]))
    prefill_s = time.perf_counter() - t0
    _fence(jnp.sum(run(new)[:, -1]))  # compile decode scan
    t0 = time.perf_counter()
    _fence(jnp.sum(run(new)[:, -1]))
    total_s = time.perf_counter() - t0
    decode_s = max(total_s - prefill_s, 1e-9)
    return {
        "prompt": prompt_len, "new": new,
        "prefill_s": round(prefill_s, 3),
        "prefill_tokens_per_sec": round(prompt_len / prefill_s),
        "decode_tokens_per_sec": round((new - 1) / decode_s),
    }


def bench_dropout_identity():
    """In-kernel dropout backward verification (real TPU only): out is
    linear in v with a fixed seed, so <loss(v+u) - loss(v)> must equal
    <u, grad_v loss> EXACTLY when the backward kernels regenerate the
    forward's masks (tests/test_flash_dropout_tpu.py's identity)."""
    from solvingpapers_tpu.kernels import flash_attention
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend

    if not is_tpu_backend():
        return {"skipped": "requires the hardware PRNG (real TPU)"}
    key = jax.random.key(7)
    kq, kk, kv, kw, ku = jax.random.split(key, 5)
    q = jax.random.normal(kq, (1, 256, 2, 32))
    k = jax.random.normal(kk, (1, 256, 2, 32))
    v = jax.random.normal(kv, (1, 256, 2, 32))
    w = jax.random.normal(kw, q.shape)
    u = jax.random.normal(ku, v.shape)

    def loss(v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                            dropout_seed=11) * w
        )

    gv = jax.grad(loss)(v)
    lhs = _fence(loss(v + u)) - _fence(loss(v))
    rhs = _fence(jnp.sum(u * gv))
    rel = abs(lhs - rhs) / max(abs(rhs), 1e-9)
    return {"rel_err": round(rel, 5), "pass": bool(rel < 2e-2)}


def main() -> None:
    rows = []
    primary = None
    for name, fn in (
        ("gpt_charlm_train", bench_gpt_train),
        ("llama3_350m_mfu", bench_350m_mfu),
        ("flash_mla_16k_step", bench_flash_mla_16k),
        ("decode_llama3_350m", bench_decode),
        ("decode_dsv3_16k_prefill", bench_decode_16k_prefill),
        ("flash_dropout_linearity", bench_dropout_identity),
    ):
        try:
            res = {"name": name, **fn()}
        except Exception as e:  # isolate rows; record the failure
            res = {"name": name, "error": repr(e)[:300]}
        rows.append(res)
        if name == "gpt_charlm_train":
            primary = res

    out = {
        "metric": "gpt_charlm_train_tokens_per_sec",
        "value": primary.get("tokens_per_sec", 0.0),
        "unit": "tokens/sec",
        "vs_baseline": primary.get("vs_baseline", 0.0),
        "detail": {
            "config": "gpt-jax.ipynb cell 8 (bs128 x block256, dim256, L8)",
            "baseline": "16.1k tok/s on 1x T4 (reference cell 18)",
            "device": str(jax.devices()[0].device_kind),
        },
        "scorecard": rows,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
