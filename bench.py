"""Benchmark of record — prints ONE JSON line.

Primary metric (top-level keys, driver contract): the reference's own GPT
char-LM training config (gpt/gpt-jax.ipynb cell 8: batch 128 x block 256 =
32,768 tok/step, dim 256, 1 head, 8 layers) trained with AdamW in bf16 on
this repo's engine, vs the reference's measured ~16.1k tok/s (1x T4,
BASELINE.md). Metric: steady-state training tokens/sec.

`scorecard` (same JSON line): the full driver-visible surface the round-2
verdict asked for (missing item 5) — the 350M MFU study point, flash-MLA
16k step time, cached-decode throughput incl. a 16k-prompt prefill row,
and (on real TPU) the in-kernel dropout linearity identity, so the
kernel's riskiest path is verified every round. Each row is isolated: a
failure records {"error": ...} instead of killing the bench.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_TOK_S = 16_100.0  # gpt-jax.ipynb cell 18 tqdm, 1x T4


def _fence(x) -> float:
    # device_get of a dependent scalar: block_until_ready is not a real
    # fence on the axon-tunnelled platform
    return float(jax.device_get(x))


def _marginal_row(t_long, t_short, n_delta, prefix, batch=1):
    """Marginal-cost keys for a decode row: (T_long - T_short) / n_delta
    steps cancels the tunnel's ~110 ms fixed per-program latency. Units
    mirror the rows' unsuffixed keys exactly — tokens/sec counts
    DELIVERED tokens (batch rows per step), ms_per_token is per SCAN STEP
    — so suffixed and unsuffixed values differ only by the cancelled
    fixed latency. Records an error key instead of clamping when the two
    separately-timed runs cross (a clamped near-zero marginal would
    masquerade as an absurd tokens/sec, the r3 31e9 artifact class)."""
    if t_long > t_short:
        step_s = (t_long - t_short) / n_delta
        return {
            f"{prefix}tokens_per_sec_marginal": round(batch / step_s),
            f"{prefix}ms_per_token_marginal": round(step_s * 1e3, 3),
        }
    return {f"{prefix}marginal_error":
            "t_long <= t_short; marginal unmeasurable"}


def _timed_windows(step, n_steps=40, n_windows=3, warmup=20):
    """Best-of-N windows of `n_steps` steps; step() must return a scalar-
    fence-able value. The tunnelled device has bursty transport noise, so
    the minimum is the honest steady-state figure."""
    for _ in range(warmup):
        out = step()
    _fence(out)
    windows = []
    for _ in range(n_windows):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            out = step()
        _fence(out)
        windows.append(time.perf_counter() - t0)
    return min(windows) / n_steps, sum(windows) / (n_windows * n_steps)


def bench_gpt_train():
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend
    from solvingpapers_tpu.metrics.mfu import (
        chip_peak_flops, transformer_flops_per_token,
    )
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    # the framework's fast path: Pallas flash attention with in-kernel
    # dropout (same Bernoulli semantics as the reference's prob dropout).
    # Off-TPU smoke runs use the dense path.
    cfg = GPTConfig(
        vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
        dropout=0.1, dtype="bfloat16", use_flash=is_tpu_backend(),
    )
    batch, scan_k = 128, 8
    tcfg = TrainConfig(
        steps=0, batch_size=batch, log_every=10_000, eval_every=0,
        scan_steps=scan_k,
        optimizer=OptimizerConfig(name="adamw", max_lr=1e-3, total_steps=1000),
    )
    from solvingpapers_tpu.data.batches import random_crop_batch

    trainer = Trainer(GPT(cfg), tcfg)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, size=1_000_000)
    )
    key = jax.random.key(0)

    @jax.jit
    def make_window(k):
        # all scan_k batches cropped on-device in ONE dispatch (same
        # random-crop distribution as lm_batch_iterator, which would issue
        # scan_k crop dispatches + a stack)
        x, y = random_crop_batch(toks, k, scan_k * batch, cfg.block_size)
        return {"x": x.reshape(scan_k, batch, cfg.block_size),
                "y": y.reshape(scan_k, batch, cfg.block_size)}

    counter = iter(range(1_000_000))

    def next_window():
        return make_window(jax.random.fold_in(key, next(counter)))

    state = trainer.init_state(jax.tree.map(lambda a: a[0], next_window()))
    trainer._build_steps()
    holder = {"state": state}

    def step():
        # one dispatch = scan_k on-device train steps (TrainConfig.scan_steps
        # — the engine's fit() path for small models); equality with
        # sequential stepping is pinned by test_scan_steps_window_equals_...
        holder["state"], metrics = trainer._train_step_scan(
            holder["state"], next_window()
        )
        return metrics["train_loss"]

    dt, dt_mean = _timed_windows(step, n_steps=10, n_windows=3, warmup=4)
    dt, dt_mean = dt / scan_k, dt_mean / scan_k
    tok_s = batch * cfg.block_size / dt
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    fpt = transformer_flops_per_token(
        n_params, cfg.n_layers, cfg.dim, cfg.block_size
    )
    return {
        "tokens_per_sec": round(tok_s, 1),
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
        "step_time_ms": round(1000 * dt, 2),
        "step_time_ms_mean": round(1000 * dt_mean, 2),
        "mfu": round(tok_s * fpt / chip_peak_flops(), 4),
        "n_params": int(n_params),
    }


def bench_350m_mfu():
    """The 342M llama3 single-chip MFU point (tools/scale_350m.py row):
    dim 1024, 24 layers, 16q/8kv heads, seq 1024, bf16, flash."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend
    from solvingpapers_tpu.metrics.mfu import (
        chip_peak_flops, transformer_flops_per_token,
    )
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    bs, seq = 8, 1024
    cfg = LlamaConfig(
        vocab_size=32_000, max_seq_len=seq, dim=1024, n_layers=24,
        n_heads=16, n_kv_heads=8, dropout=0.0, dtype="bfloat16",
        use_flash=is_tpu_backend(),
    )
    tcfg = TrainConfig(
        steps=0, batch_size=bs, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=3e-4, total_steps=100),
    )
    trainer = Trainer(Llama(cfg), tcfg)
    toks = np.random.default_rng(1).integers(0, cfg.vocab_size, size=500_000)
    it = lm_batch_iterator(toks, bs, seq, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    holder = {"state": state}

    def step():
        holder["state"], metrics = trainer._train_step(
            holder["state"], next(it)
        )
        return metrics["train_loss"]

    dt, _ = _timed_windows(step, n_steps=10, n_windows=3, warmup=8)
    tok_s = bs * seq / dt
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    fpt = transformer_flops_per_token(n_params, cfg.n_layers, cfg.dim, seq)
    return {
        "tokens_per_sec": round(tok_s, 1),
        "step_time_ms": round(1000 * dt, 2),
        "mfu": round(tok_s * fpt / chip_peak_flops(), 4),
        "n_params": int(n_params),
    }


def bench_flash_mla_16k():
    """dsv3_long's core claim: a 16,384-token flagship train step on one
    chip via flash-MLA + remat (the dense path cannot even compile)."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    seq = 16_384
    cfg = DeepSeekV3Config(
        vocab_size=32_000, block_size=seq, dtype="bfloat16", use_flash=True,
        remat=True, pe_scale=0.02, rope_dim=64, dropout=0.0, attn_dropout=0.0,
    )
    tcfg = TrainConfig(
        steps=0, batch_size=1, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=3e-4, total_steps=100),
    )
    trainer = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                      init_fn=dsv3_init_fn)
    toks = np.random.default_rng(2).integers(0, cfg.vocab_size, size=200_000)
    it = lm_batch_iterator(toks, 1, seq, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    holder = {"state": state}

    def step():
        holder["state"], metrics = trainer._train_step(
            holder["state"], next(it)
        )
        return metrics["train_loss"]

    dt, _ = _timed_windows(step, n_steps=5, n_windows=2, warmup=3)
    return {
        "seq": seq,
        "step_time_ms": round(1000 * dt, 2),
        "tokens_per_sec": round(seq / dt, 1),
    }


def bench_decode():
    """Cached scan decode (llama3 d1024 L24) — the reference re-runs the
    full forward per token (SURVEY.md §3.4).

    Round 5: marginal timing — (T(256 new) - T(64 new)) / 192 — cancels
    the tunnel's ~110 ms fixed per-program latency, which was ~20% of the
    256-token wall and the round-to-round noise in this row (r3 3664,
    r4 3929, r5 quiet re-run 3626 'tok/s' under the old end-to-end
    method, all the same device). Raw walls stay in the row for audit."""
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.infer import generate
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    bs, prompt_len, new, new_short = 8, 128, 256, 64
    cfg = LlamaConfig(
        vocab_size=32_000, dim=1024, n_layers=24, n_heads=16, n_kv_heads=8,
        max_seq_len=prompt_len + new, dropout=0.0, dtype="bfloat16",
    )
    model = Llama(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (bs, prompt_len)),
        jnp.int32,
    )
    params = model.init({"params": jax.random.key(0)}, prompt)["params"]
    rng = jax.random.key(1)

    def timed(n_new):
        def run():
            return generate(model, params, prompt, rng, max_new_tokens=n_new,
                            sampler=ops.sample_greedy,
                            max_len=prompt_len + new)

        _fence(jnp.sum(run()[:, -1]))  # compile
        # min-of-5: this row's gated tokens_per_sec swung r3 3664 / r4
        # 3929 / r5 3626 purely on tunnel-transport jitter at min-of-3
        return min(
            (lambda t0: (_fence(jnp.sum(run()[:, -1])),
                         time.perf_counter() - t0)[1])(time.perf_counter())
            for _ in range(5)
        )

    t_long = timed(new)
    t_short = timed(new_short)
    # transition round: `tokens_per_sec` keeps the END-TO-END method so the
    # vs_prior gate compares like with like; the marginal figure rides
    # alongside and becomes the gated key next round
    return {
        "bs": bs, "prompt": prompt_len, "new": new,
        "tokens_per_sec": round(bs * new / t_long),
        "ms_per_token": round(t_long / new * 1e3, 3),
        "wall_s_64": round(t_short, 3),
        "wall_s_256": round(t_long, 3),
        **_marginal_row(t_long, t_short, new - new_short, "", batch=bs),
    }


def bench_decode_16k_prefill():
    """Long-context generation: 16k-token prompt prefill through the
    end-aligned flash path into the MLA latent cache, then scan decode.

    Prefill and decode are each timed DIRECTLY as separate jitted programs
    over the same cache state — round 3 subtracted two independently
    measured end-to-end runs and the noise-dominated difference produced a
    nonsense decode number (VERDICT r3 'what's weak' #1).

    Decode timing (round 5): the tunnelled platform carries a measured
    ~110 ms FIXED latency per program execution (a jitted x+1 round-trips
    in 110 ms; a 1000-step trivial scan in 108 ms), so round 4's
    "3.9 ms/token" over a 32-token scan was ~3.4 ms/token of tunnel
    overhead, not decode. The steady-state number a real serving loop
    sees is the MARGINAL cost — (T(128 tokens) - T(32 tokens)) / 96 —
    reported in the *_marginal keys with both raw walls kept for audit
    (the unsuffixed keys keep the r4-comparable end-to-end method for one
    transition round so the vs_prior gate compares like with like).
    The same profiling killed the planned blockwise cached-decode kernel
    with data: per-token time is FLAT in cache length (1.61 ms @ 4k vs
    1.75 ms @ 16k cache) and nearly flat in depth (1.50 ms @ 1 layer vs
    1.75 @ 6), i.e. bs-1 decode is per-op-overhead-bound, not
    attention-bound — so the lever is batch, and the bs=8 row below
    amortizes exactly that."""
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

    prompt_len, new, chunk = 16_384, 32, 2048
    new_long = 128
    # the cache/position budget must cover the LONG timing arm — 32 slots
    # would silently clamp the 128-token program's tail writes
    total = prompt_len + new_long
    cfg = DeepSeekV3Config(
        vocab_size=32_000, block_size=total, dtype="bfloat16",
        use_flash=True, pe_scale=0.02, rope_dim=64, dropout=0.0,
        attn_dropout=0.0,
    )
    model = DeepSeekV3(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (1, prompt_len)),
        jnp.int32,
    )
    variables = model.init({"params": jax.random.key(2)},
                           jnp.zeros((1, 8), jnp.int32))

    @jax.jit
    def prefill(variables, prompt):
        caches = model.init_caches(1, total)
        logits = None
        for start in range(0, prompt_len, chunk):  # unrolled static chunks
            end = start + chunk
            tok = jax.lax.slice_in_dim(prompt, start, end, axis=1)
            positions = jnp.broadcast_to(jnp.arange(start, end), (1, chunk))
            logits, caches = model.apply(
                variables, tok, positions=positions, caches=caches,
                deterministic=True, attend_len=end,
            )
        return logits, caches

    @functools.partial(jax.jit, static_argnames=("length",))
    def decode(variables, first_tok, caches, rng, length=new):
        b = first_tok.shape[0]

        def body(carry, _):
            tok, pos, caches, rng = carry
            logits, caches = model.apply(
                variables, tok[:, None],
                positions=jnp.broadcast_to(pos[None, None], (b, 1)),
                caches=caches, deterministic=True,
            )
            rng, sub = jax.random.split(rng)
            nt = ops.sample_greedy(logits[:, -1], sub).astype(tok.dtype)
            return (nt, pos + 1, caches, rng), nt

        _, toks = jax.lax.scan(
            body, (first_tok, jnp.asarray(prompt_len), caches, rng), None,
            length=length,
        )
        return toks

    rng = jax.random.key(3)
    logits, caches = prefill(variables, prompt)  # compile
    _fence(jnp.sum(logits[:, -1]))
    prefill_s = min(
        (lambda t0: (
            _fence(jnp.sum(prefill(variables, prompt)[0][:, -1])),
            time.perf_counter() - t0,
        )[1])(time.perf_counter())
        for _ in range(3)
    )
    first_tok = ops.sample_greedy(logits[:, -1], rng).astype(prompt.dtype)

    def time_decode(tok, caches, length):
        _fence(jnp.sum(decode(variables, tok, caches, rng, length=length)))
        return min(
            (lambda t0: (
                _fence(jnp.sum(
                    decode(variables, tok, caches, rng, length=length)
                )),
                time.perf_counter() - t0,
            )[1])(time.perf_counter())
            for _ in range(3)
        )

    t_short = time_decode(first_tok, caches, new)
    t_long = time_decode(first_tok, caches, new_long)

    # bs=8 decode over the same 16k-deep cache (per-op overhead amortizes
    # across the batch; prompt processing replicated via tiled caches)
    bs = 8
    caches8 = jax.tree.map(lambda a: jnp.tile(a, (bs,) + (1,) * (a.ndim - 1)),
                           caches)
    tok8 = jnp.tile(first_tok, (bs,))
    t8_short = time_decode(tok8, caches8, new)
    t8_long = time_decode(tok8, caches8, new_long)

    # transition round: `decode_tokens_per_sec` keeps the END-TO-END
    # method (r4-comparable; dominated by the ~110 ms tunnel latency at 32
    # tokens — see docstring); the marginal keys carry the honest
    # steady-state figure and become the gated keys next round
    return {
        "prompt": prompt_len, "new": new,
        "prefill_s": round(prefill_s, 3),
        "prefill_tokens_per_sec": round(prompt_len / prefill_s),
        "decode_tokens_per_sec": round(new / t_short),
        "decode_ms_per_token": round(t_short / new * 1e3, 3),
        "decode_wall_s_32": round(t_short, 3),
        "decode_wall_s_128": round(t_long, 3),
        **_marginal_row(t_long, t_short, new_long - new, "decode_"),
        **_marginal_row(t8_long, t8_short, new_long - new, "decode_bs8_",
                        batch=bs),
    }


def bench_speculative_decode():
    """MTP self-speculative decoding vs plain greedy decode on a briefly
    trained dsv3+MTP model (acceptance tracks model quality, so random
    params would only measure the fallback path). Output equality is
    pinned by tests/test_speculative.py; this row records the measured
    acceptance and the wall-clock ratio at the flagship's dims — where
    per-forward latency dominates and the forward savings become wall
    time (at toy dims decode is op-count-bound and the extra MTP-head
    pass eats the win: dim 256/L4 measured 0.78x)."""
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.infer import generate, generate_speculative
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=512, dim=512, n_layers=6, n_heads=8,
        latent_dim=64, rope_dim=32, pe_scale=0.02, n_experts=8,
        top_experts=2, dropout=0.0, attn_dropout=0.0, mtp_heads=2,
        dtype="bfloat16",
    )
    model = DeepSeekV3(cfg)
    # word-structured synthetic text: predictable enough for real
    # acceptance after a short burst, not a degenerate loop
    from solvingpapers_tpu.data.synthetic import synthetic_text

    text = synthetic_text(400_000, seed=5)
    vocab = sorted(set(text))[: cfg.vocab_size]
    lut = {c: i for i, c in enumerate(vocab)}
    toks = np.asarray([lut.get(c, 0) for c in text], np.int32)
    tcfg = TrainConfig(
        steps=400, batch_size=32, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=1e-3, warmup_steps=40,
                                  total_steps=400),
    )
    trainer = Trainer(model, tcfg, loss_fn=dsv3_loss_fn, init_fn=dsv3_init_fn)
    state = trainer.fit(lm_batch_iterator(toks, 32, 256, seed=0))
    # keep params device-resident: a device_get here would re-ship the
    # whole model host->device on every timed call
    params = state.params
    extra = {"moe_state": state.model_state["moe_state"]}

    prompt = jnp.asarray(toks[:64][None, :], jnp.int32)
    new = 128
    rng = jax.random.key(0)

    def plain():
        return generate(model, params, prompt, rng, max_new_tokens=new,
                        sampler=ops.sample_greedy, extra_variables=extra,
                        max_len=prompt.shape[1] + new + 2)

    def spec(n_drafts=1):
        return generate_speculative(model, params, prompt,
                                    max_new_tokens=new,
                                    extra_variables=extra,
                                    n_drafts=n_drafts)

    _fence(jnp.sum(plain()[:, -1]))
    plain_s = min(
        (lambda t0: (_fence(jnp.sum(plain()[:, -1])),
                     time.perf_counter() - t0)[1])(time.perf_counter())
        for _ in range(3)
    )

    def time_spec(n_drafts):
        out, stats = spec(n_drafts)
        _fence(jnp.sum(out[:, -1]))
        s = min(
            (lambda t0: (_fence(jnp.sum(spec(n_drafts)[0][:, -1])),
                         time.perf_counter() - t0)[1])(time.perf_counter())
            for _ in range(3)
        )
        f = int(jax.device_get(stats["forwards"]))
        a = int(jax.device_get(stats["accepted"]))
        return s, f, a

    spec_s, f, a = time_spec(1)
    # chained 2-head drafts (round 5): both trained MTP heads draft, cap 3
    # tokens/forward — must push tokens/forward past the 1-draft cap of 2
    spec2_s, f2, a2 = time_spec(2)
    return {
        "new_tokens": new,
        "forwards": f,
        "accepted": a,
        "tokens_per_forward": round((f + a) / max(f, 1), 3),
        "plain_ms_per_token": round(plain_s / new * 1e3, 3),
        "spec_ms_per_token": round(spec_s / new * 1e3, 3),
        "wall_speedup": round(plain_s / spec_s, 3),
        "draft2_forwards": f2,
        "draft2_accepted": a2,
        "draft2_tokens_per_forward": round((f2 + a2) / max(f2, 1), 3),
        "draft2_ms_per_token": round(spec2_s / new * 1e3, 3),
        "draft2_wall_speedup": round(plain_s / spec2_s, 3),
    }


def bench_dropout_identity():
    """In-kernel dropout backward verification (real TPU only): out is
    linear in v with a fixed seed, so <loss(v+u) - loss(v)> must equal
    <u, grad_v loss> EXACTLY when the backward kernels regenerate the
    forward's masks (tests/test_flash_dropout_tpu.py's identity)."""
    from solvingpapers_tpu.kernels import flash_attention
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend

    if not is_tpu_backend():
        return {"skipped": "requires the hardware PRNG (real TPU)"}
    key = jax.random.key(7)
    kq, kk, kv, kw, ku = jax.random.split(key, 5)
    q = jax.random.normal(kq, (1, 256, 2, 32))
    k = jax.random.normal(kk, (1, 256, 2, 32))
    v = jax.random.normal(kv, (1, 256, 2, 32))
    w = jax.random.normal(kw, q.shape)
    u = jax.random.normal(ku, v.shape)

    def loss(v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, dropout_rate=0.3,
                            dropout_seed=11) * w
        )

    gv = jax.grad(loss)(v)
    lhs = _fence(loss(v + u)) - _fence(loss(v))
    rhs = _fence(jnp.sum(u * gv))
    rel = abs(lhs - rhs) / max(abs(rhs), 1e-9)
    return {"rel_err": round(rel, 5), "pass": bool(rel < 2e-2)}


# Per-row keys compared against the prior round's record (higher = better).
_GATED_KEYS = ("tokens_per_sec", "prefill_tokens_per_sec",
               "decode_tokens_per_sec", "mfu")
_REGRESSION_TOL = 0.03  # flag drops > 3%, like tools/parity_suite.py's gates


def _load_prior_scorecard():
    """Latest BENCH_r{N}.json next to this file -> (round_n, {name: row}).

    The driver wraps our JSON line under a "parsed" key; accept both the
    wrapped and the raw layout.
    """
    import glob
    import os
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    best_n, best = -1, None
    for path in glob.glob(os.path.join(here, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        n = int(m.group(1))
        if n > best_n:
            best_n, best = n, obj.get("parsed", obj)
    if not isinstance(best, dict):
        return -1, {}
    rows = best.get("scorecard", [])
    return best_n, {r["name"]: r for r in rows if isinstance(r, dict) and "name" in r}


def _gate_vs_prior(rows):
    """Annotate each row with vs_prior ratios and collect >3% regressions —
    round 3's 4.6% GPT headline drop went unnoticed because nothing in the
    repo compared rounds (VERDICT r3 'what's weak' #2)."""
    prior_n, prior = _load_prior_scorecard()
    regressions = []
    for row in rows:
        ref = prior.get(row.get("name"))
        if not ref:
            continue
        vs = {}
        for key in _GATED_KEYS:
            cur, old = row.get(key), ref.get(key)
            if not (isinstance(cur, (int, float)) and isinstance(old, (int, float))):
                continue
            if old <= 0 or not np.isfinite(old) or old > 1e9:
                # prior record invalid (e.g. r3's 31e9 tok/s decode artifact)
                vs[key] = {"prior": old, "note": "prior value invalid; skipped"}
                continue
            ratio = cur / old
            vs[key] = round(ratio, 4)
            if ratio < 1.0 - _REGRESSION_TOL:
                regressions.append(
                    {"row": row["name"], "key": key, "prior": old,
                     "current": cur, "ratio": round(ratio, 4)}
                )
        if vs:
            row["vs_prior"] = vs
    return prior_n, regressions


def main() -> None:
    rows = []
    primary = None
    for name, fn in (
        ("gpt_charlm_train", bench_gpt_train),
        ("llama3_350m_mfu", bench_350m_mfu),
        ("flash_mla_16k_step", bench_flash_mla_16k),
        ("decode_llama3_350m", bench_decode),
        ("decode_dsv3_16k_prefill", bench_decode_16k_prefill),
        ("mtp_speculative_decode", bench_speculative_decode),
        ("flash_dropout_linearity", bench_dropout_identity),
    ):
        try:
            res = {"name": name, **fn()}
        except Exception as e:  # isolate rows; record the failure
            res = {"name": name, "error": repr(e)[:300]}
        rows.append(res)
        if name == "gpt_charlm_train":
            primary = res

    prior_round, regressions = _gate_vs_prior(rows)
    out = {
        "metric": "gpt_charlm_train_tokens_per_sec",
        "value": primary.get("tokens_per_sec", 0.0),
        "unit": "tokens/sec",
        "vs_baseline": primary.get("vs_baseline", 0.0),
        "detail": {
            "config": "gpt-jax.ipynb cell 8 (bs128 x block256, dim256, L8)",
            "baseline": "16.1k tok/s on 1x T4 (reference cell 18)",
            "device": str(jax.devices()[0].device_kind),
        },
        "prior_round": prior_round,
        "regressions_vs_prior": regressions,
        "scorecard": rows,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
