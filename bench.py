"""Benchmark of record — prints ONE JSON line.

Workload: the reference's own GPT char-LM training config
(gpt/gpt-jax.ipynb cell 8: batch 128 x block 256 = 32,768 tok/step,
dim 256, 1 head, 8 layers), trained with AdamW in bf16 on this repo's
engine. Baseline: the reference's measured ~16.1k tok/s on its hardware
(1x T4, BASELINE.md). Metric: steady-state training tokens/sec.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def main() -> None:
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.metrics.mfu import chip_peak_flops, transformer_flops_per_token
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    BASELINE_TOK_S = 16_100.0  # gpt-jax.ipynb cell 18 tqdm, 1x T4

    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend

    # the framework's fast path: Pallas flash attention with in-kernel
    # dropout (same Bernoulli semantics as the reference's prob dropout;
    # measured ~22% faster than the dense path on this workload). Off-TPU
    # smoke runs use the dense path (apply_flash_attention would fall back
    # per-call anyway; this keeps the measured graph uniform).
    cfg = GPTConfig(
        vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
        dropout=0.1, dtype="bfloat16", use_flash=is_tpu_backend(),
    )
    batch = 128
    tcfg = TrainConfig(
        steps=0, batch_size=batch, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(name="adamw", max_lr=1e-3, total_steps=1000),
    )
    trainer = Trainer(GPT(cfg), tcfg)

    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=1_000_000)
    it = lm_batch_iterator(toks, batch, cfg.block_size, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()

    # compile + warmup; fence via value fetch (block_until_ready does not
    # actually sync on the axon-tunnelled TPU platform). Warmup long enough
    # to fill the dispatch queue — short warmups leave first-window
    # stragglers that inflate the measurement by ~40%
    for _ in range(20):
        state, metrics = trainer._train_step(state, next(it))
    float(jax.device_get(metrics["train_loss"]))

    # 3 timed windows, best wins: the tunnelled device has bursty transport
    # noise (observed 23-32 ms/step across identical runs); the minimum is
    # the honest steady-state figure
    n_steps = 40
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            state, metrics = trainer._train_step(state, next(it))
        float(jax.device_get(metrics["train_loss"]))
        windows.append(time.perf_counter() - t0)
    dt = min(windows)

    tok_per_step = batch * cfg.block_size
    tok_s = n_steps * tok_per_step / dt

    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    fpt = transformer_flops_per_token(n_params, cfg.n_layers, cfg.dim, cfg.block_size)
    mfu = tok_s * fpt / chip_peak_flops()

    print(json.dumps({
        "metric": "gpt_charlm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(tok_s / BASELINE_TOK_S, 3),
        "detail": {
            "config": "gpt-jax.ipynb cell 8 (bs128 x block256, dim256, L8)",
            "baseline": "16.1k tok/s on 1x T4 (reference cell 18)",
            "step_time_ms": round(1000 * dt / n_steps, 2),
            # the mean across windows, for honesty about transport noise
            # (the min is the reported steady-state figure)
            "step_time_ms_mean": round(
                1000 * sum(windows) / (len(windows) * n_steps), 2
            ),
            "tokens_per_sec_mean": round(
                len(windows) * n_steps * tok_per_step / sum(windows), 1
            ),
            "mfu": round(mfu, 4),
            "n_params": int(n_params),
            "device": str(jax.devices()[0].device_kind),
        },
    }))


if __name__ == "__main__":
    main()
