"""Diagnose the gemma_markov quality gap (VERDICT r4 ask 6).

gemma_markov posts gap-to-entropy 0.139 nats vs llama3's 0.088 and gpt's
0.093 at near-identical scale. The suspect list from the verdict: the
grouped-MQA formulation, GeGLU init/activation, and the RoPE path. The
attention/RoPE stack is literally the same shared module as llama3's
(models/layers.py Attention), so the ablation matrix focuses on what
actually differs: activation (gelu_tanh vs silu), FFN width (4*dim vs
SwiGLU's (2/3)*4*dim), kv grouping, corpus size (memorization — the dsv3
diagnosis), and learning rate.

Usage: python tools/gemma_markov_ablation.py [--steps 3000] [variants...]
Prints one JSON line per variant: {"variant", "val_loss", "gap", ...}.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time


def run_variant(name: str, steps: int) -> dict:
    import jax  # noqa: F401

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run, init_fn_for, loss_fn_for, rules_for,
    )
    from solvingpapers_tpu.data.synthetic import markov_entropy_nats
    from solvingpapers_tpu.sharding import batch_sharding, create_mesh
    from solvingpapers_tpu.train import Trainer

    cfg = get_config("gemma_markov", steps=steps)
    model_over: dict = {}
    data_over: dict = {}
    train_over: dict = {}

    if name == "base":
        pass
    elif name == "silu":
        # GeGLU -> SwiGLU activation at equal width (GemmaConfig knob)
        model_over["activation"] = "silu"
    elif name == "swiglu_width":
        # llama's (2/3)*4*dim hidden at gemma's gelu gating
        from solvingpapers_tpu.models.layers import swiglu_hidden_dim

        model_over["hidden_dim"] = swiglu_hidden_dim(cfg.model.dim)
    elif name == "mha":
        model_over["n_kv_heads"] = cfg.model.n_heads
    elif name == "data16m":
        data_over["n_chars"] = 16_000_000
    elif name == "lr5e-4":
        train_over["optimizer"] = dataclasses.replace(
            cfg.train.optimizer, max_lr=5e-4
        )
    elif name == "layers3":
        model_over["n_layers"] = 3
    else:
        raise SystemExit(f"unknown variant {name}")

    if model_over:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, **model_over)
        )
    if data_over:
        cfg = dataclasses.replace(cfg, data={**cfg.data, **data_over})
    if train_over:
        cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, **train_over)
        )
    mesh = create_mesh(cfg.train.mesh)
    cfg, model, _, train_iter, eval_iter_fn = build_char_lm_run(
        cfg, sharding=batch_sharding(mesh)
    )
    trainer = Trainer(model, cfg.train, loss_fn=loss_fn_for(cfg),
                      init_fn=init_fn_for(cfg), mesh=mesh,
                      rules=rules_for(cfg))
    t0 = time.perf_counter()
    state = trainer.fit(train_iter)
    val = trainer.evaluate(state, eval_iter_fn())
    wall = time.perf_counter() - t0
    h = markov_entropy_nats(cfg.data)
    return {
        "variant": name,
        "steps": steps,
        "val_loss": round(float(val["val_loss"]), 5),
        "entropy_nats": round(h, 5),
        "gap": round(float(val["val_loss"]) - h, 5),
        "wall_s": round(wall, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("variants", nargs="*", default=None)
    ap.add_argument("--steps", type=int, default=3000)
    args = ap.parse_args()
    variants = args.variants or [
        "base", "silu", "swiglu_width", "mha", "data16m", "lr5e-4", "layers3",
    ]
    for v in variants:
        print(json.dumps(run_variant(v, args.steps)), flush=True)


if __name__ == "__main__":
    main()
