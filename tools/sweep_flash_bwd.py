"""Flash-attention BACKWARD block sweep at long sequence (VERDICT r4 ask 8).

The forward sweep (tools/scale_350m.py) moved 1k-seq MFU 35.9% -> 52.2% and
pinned DEFAULT_BLOCK=512; nothing equivalent exists for the backward at the
16k sequence the kernel was rebuilt for (16k-context training MFU 40.4% vs
the >=45% north star). This times value_and_grad of the kernel itself at
the flagship's 16k MLA shape (q (1,16k,8,128) vs MQA latents (1,16k,1,128))
and the GQA llama shape, across (block_q, block_k) grids, fwd-only vs
fwd+bwd, so the step-level number can be attributed.

Usage: python tools/sweep_flash_bwd.py [--seq 16384]
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=16384)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from solvingpapers_tpu.kernels.flash_attention import flash_attention

    seq = args.seq

    REPS = 20  # in-program repeats: the tunnelled device adds ~110 ms of
    # fixed per-program latency, so a single kernel call is unmeasurable —
    # scan the kernel inside ONE program until its time dominates

    def bench(shape_name, n_heads, n_kv, d, block_q, block_k, mode):
        q = jax.random.normal(jax.random.key(0), (1, seq, n_heads, d),
                              jnp.bfloat16)
        k = jax.random.normal(jax.random.key(1), (1, seq, n_kv, d),
                              jnp.bfloat16)
        v = jax.random.normal(jax.random.key(2), (1, seq, n_kv, d),
                              jnp.bfloat16)

        def one(q):
            return flash_attention(
                q, k, v, causal=True, block_q=block_q, block_k=block_k
            )

        if mode == "fwd":
            @jax.jit
            def run(q):
                def body(c, _):
                    # feed the output back so iterations can't be collapsed
                    return one(c).astype(c.dtype), None
                out, _ = jax.lax.scan(body, q, None, length=REPS)
                return jnp.sum(out.astype(jnp.float32))
        else:
            @jax.jit
            def run(q):
                def body(c, _):
                    g = jax.grad(lambda q: jnp.sum(
                        one(q).astype(jnp.float32)))(c)
                    return g.astype(c.dtype), None
                out, _ = jax.lax.scan(body, q, None, length=REPS)
                return jnp.sum(out.astype(jnp.float32))

        out = run(q)
        float(jax.device_get(out))  # compile + real sync
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            out = run(q)
            float(jax.device_get(out))
            best = min(best, time.perf_counter() - t0)
        # subtract the measured fixed program latency so rows are the
        # kernel's own time
        return (best - 0.110) / REPS * 1e3

    for shape_name, n_heads, n_kv, d in (
        ("mla_16k", 8, 1, 128),
        ("gqa_16k", 16, 4, 64),
    ):
        for mode in ("fwd", "fwd+bwd"):
            for bq, bk in ((256, 256), (256, 512), (512, 256), (512, 512),
                           (512, 1024), (1024, 512), (1024, 1024)):
                ms = bench(shape_name, n_heads, n_kv, d, bq, bk, mode)
                print(json.dumps({
                    "shape": shape_name, "mode": mode, "block_q": bq,
                    "block_k": bk, "ms": round(ms, 2),
                }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
