"""GPipe bubble-overhead measurement (BENCHMARKS.md PP row).

The ppermute schedule runs `m + S - 1` ticks for m microbatches over S
stages; (S-1) of them are bubbles, so the analytic bubble fraction is
(S-1)/(m+S-1) of every step — amortized away as m grows at fixed global
batch (each tick's compute shrinks by the same factor the tick count
grows, up to per-tick overheads).

Multi-chip hardware is not attached here, so this measures on the virtual
CPU mesh (same schedule, same collectives, host math): the MEASURED
step-time trend vs m validates the schedule's amortization shape, while
the analytic fraction is the hardware-independent number. Run with
JAX_PLATFORMS=cpu and XLA_FLAGS=--xla_force_host_platform_device_count=8
(tests/conftest.py's recipe), or let this script set them via a subprocess
re-exec (default when the attached platform has <8 devices).

Usage: python tools/bench_pipeline.py [--stages 4] [--batch 32]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time


def _body(n_stages: int, batch: int) -> None:
    import jax
    import numpy as np

    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
    from solvingpapers_tpu.sharding import MeshConfig, PP_RULES, batch_sharding, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    mesh_cfg = MeshConfig(data=8 // n_stages, pipe=n_stages)
    mesh = create_mesh(mesh_cfg, jax.devices()[:8])
    rows = []
    # (n_micro, virtual_stages): v > 1 = interleaved schedule, bubble
    # (P-1)/(m*v + P - 1) — same total layers, thinner stages
    plan = [(1, 1), (2, 1), (4, 1), (8, 1), (4, 2), (8, 2)]
    for n_micro, v in plan:
        if (batch // (8 // n_stages)) % n_micro:
            continue
        if v > 1 and n_micro % n_stages:  # interleaved: groups of P
            continue
        cfg = GPTPipeConfig(
            vocab_size=256, block_size=128, dim=128, n_layers=n_stages * 2,
            n_heads=4, n_stages=n_stages * v, n_microbatches=n_micro,
            virtual_stages=v, pipeline_parallel=True,
        )
        tcfg = TrainConfig(
            steps=0, batch_size=batch, log_every=10_000, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True,
            optimizer=OptimizerConfig(max_lr=1e-3, total_steps=10),
        )
        trainer = Trainer(GPTPipe(cfg), tcfg, rules=PP_RULES, mesh=mesh)
        toks = np.random.default_rng(0).integers(0, 256, size=100_000)
        it = lm_batch_iterator(toks, batch, cfg.block_size,
                               sharding=batch_sharding(mesh))
        b0 = next(it)
        state = trainer.init_state(b0)
        trainer._build_steps()
        for _ in range(3):
            state, m = trainer._train_step(state, next(it))
        float(jax.device_get(m["train_loss"]))
        t0 = time.perf_counter()
        n = 10
        for _ in range(n):
            state, m = trainer._train_step(state, next(it))
        float(jax.device_get(m["train_loss"]))
        dt = (time.perf_counter() - t0) / n
        ticks = n_micro * v + n_stages - 1
        rows.append({
            "n_stages": n_stages, "n_micro": n_micro, "virtual": v,
            "ticks": ticks,
            "bubble_fraction": round((n_stages - 1) / ticks, 4),
            "step_time_ms": round(1000 * dt, 2),
        })
        print(json.dumps(rows[-1]), flush=True)
    # amortization check: more microbatches must not be slower than m=1
    if len(rows) >= 2 and rows[-1]["step_time_ms"] > rows[0]["step_time_ms"] * 1.2:
        print(json.dumps({"warning": "no amortization measured "
                          "(per-tick overhead dominates at this scale)"}))

    _memory_body(n_stages)
    _memory_body_1f1b(n_stages)
    # production-ish scale (~100M stage stack, dim 1024, seq 1024):
    # memory_analysis is compile-only, so the CPU mesh measures it fine
    _memory_body(n_stages, batch=16, seq=1024, dim=1024)
    _memory_body_1f1b(n_stages, batch=16, seq=1024, dim=1024)


def _memory_body(n_stages: int, batch: int = 64, seq: int = 512,
                 dim: int = 256) -> None:
    """Live-memory study (BENCHMARKS.md PP memory table): XLA's compiled
    memory_analysis for the PP train step — temp_size is the peak live
    temp-buffer footprint per device, which is where the backward's saved
    activations land. Compares one full-batch GPipe flush against
    pp_grad_groups sequential flushes (loss+backward per group, grads
    accumulated): with n_microbatches = pipe size per flush, residual
    memory covers one group's ticks instead of the whole batch's —
    live activations scale with n_stages, not total microbatches.
    Compile-only (no execution), so production-scale dims are measurable
    on the CPU mesh."""
    import jax
    import numpy as np

    from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
    from solvingpapers_tpu.sharding import MeshConfig, PP_RULES, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    n_micro_total = 16
    mesh_cfg = MeshConfig(data=1, pipe=n_stages)
    mesh = create_mesh(mesh_cfg, jax.devices()[:n_stages])
    x = np.random.default_rng(0).integers(0, 256, size=(batch, seq))
    b0 = {"x": x.astype(np.int32), "y": np.roll(x, -1, 1).astype(np.int32)}

    for groups in (1, n_micro_total // n_stages):
        cfg = GPTPipeConfig(
            vocab_size=256, block_size=seq, dim=dim, n_layers=n_stages * 2,
            n_heads=4, n_stages=n_stages,
            n_microbatches=n_micro_total // groups,
            pipeline_parallel=True, remat=True,
        )
        tcfg = TrainConfig(
            steps=0, batch_size=batch, log_every=10_000, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True, pp_grad_groups=groups,
            optimizer=OptimizerConfig(max_lr=1e-3, total_steps=10),
        )
        trainer = Trainer(GPTPipe(cfg), tcfg, rules=PP_RULES, mesh=mesh)
        state = trainer.init_state(b0)
        trainer._build_steps()
        stats = trainer._train_step.lower(state, b0).compile().memory_analysis()
        print(json.dumps({
            "memory_study": {
                "dim": dim, "seq": seq,
                "pp_grad_groups": groups,
                "n_microbatches_per_flush": n_micro_total // groups,
                "temp_bytes_per_device": int(stats.temp_size_in_bytes),
                "temp_mb_per_device":
                    round(stats.temp_size_in_bytes / 2**20, 1),
                "argument_mb": round(stats.argument_size_in_bytes / 2**20, 1),
            }
        }), flush=True)


def _memory_body_1f1b(n_stages: int, batch: int = 64, seq: int = 512,
                      dim: int = 256) -> None:
    """1F1B memory row (VERDICT r4 ask 4): same GPT stages, same 16
    microbatches, loss+grads in ONE pass via
    sharding.pipeline.pipeline_1f1b_value_and_grad — peak temp memory must
    beat both the single-flush GPipe backward (residuals ∝ total
    microbatches) and pp_grad_groups (residuals ∝ one group, but one
    fill+drain bubble per group) at equal microbatch count."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu import ops
    from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
    from solvingpapers_tpu.models.layers import LayerNorm
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.sharding.pipeline import (
        pipeline_1f1b_value_and_grad,
    )

    m = 16
    mesh = create_mesh(MeshConfig(data=1, pipe=n_stages),
                       jax.devices()[:n_stages])
    cfg = GPTPipeConfig(
        vocab_size=256, block_size=seq, dim=dim, n_layers=n_stages * 2,
        n_heads=4, n_stages=n_stages, n_microbatches=m,
        pipeline_parallel=True, remat=True,
    )
    model = GPTPipe(cfg)
    x = np.random.default_rng(0).integers(0, 256, (batch, seq)).astype(np.int32)
    y = np.roll(x, -1, 1).astype(np.int32)
    variables = model.init({"params": jax.random.key(0)}, jnp.asarray(x))
    p = variables["params"]
    head = {"ln_f": p["ln_f"], "lm_head": p["lm_head"]}

    def loss_fn(hp, h, target):
        z = LayerNorm().apply({"params": hp["ln_f"]}, h)
        return ops.cross_entropy(z @ hp["lm_head"]["kernel"], target)

    def step(stages_local, head, emb, pos, xx, yy):
        xe = jnp.take(emb["embedding"], xx, axis=0) + pos[None, :seq]
        micro = xe.reshape(m, batch // m, seq, dim)
        targets = yy.reshape(m, batch // m, seq)
        return pipeline_1f1b_value_and_grad(
            stages_local, head, micro, targets, model._stage_fn, loss_fn
        )

    pipe_spec = jax.tree.map(lambda _: P("pipe"), p["stages"])
    fn = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(pipe_spec, P(), P(), P(), P(), P()),
        out_specs=(P(), pipe_spec, P(), P()),
    ))
    stats = fn.lower(
        p["stages"], head, p["tok_emb"], p["pos_emb"], jnp.asarray(x),
        jnp.asarray(y),
    ).compile().memory_analysis()
    print(json.dumps({
        "memory_study": {
            "dim": dim, "seq": seq,
            "schedule": "1f1b",
            "n_microbatches_per_flush": m,
            "temp_bytes_per_device": int(stats.temp_size_in_bytes),
            "temp_mb_per_device": round(stats.temp_size_in_bytes / 2**20, 1),
            "argument_mb": round(stats.argument_size_in_bytes / 2**20, 1),
        }
    }), flush=True)


def _mesh_obs_overhead_body(n_steps: int = 24) -> None:
    """Paired ABBA mesh-obs overhead arm (the BENCH_serve.json
    trace/obs-overhead convention): a 2-stage 1F1B GPTPipe fit with
    TrainConfig.mesh_obs off (A) and on (B), run A B B A so monotonic
    load drift cancels, comparing the engine's own logged steady-state
    step_time_s. mesh_obs is observability mode (fenced dispatches +
    collective-ledger parse at compile + one stage probe outside the
    timed window); the budget it must hold is the established 2%."""
    import jax
    import numpy as np

    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
    from solvingpapers_tpu.sharding import (
        MeshConfig, PP_RULES, batch_sharding, create_mesh,
    )
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    mesh_cfg = MeshConfig(data=4, pipe=2)
    mesh = create_mesh(mesh_cfg, jax.devices()[:8])

    class _Last:
        def __init__(self):
            self.step_time = None

        def write(self, step, metrics):
            if "step_time_s" in metrics:
                self.step_time = metrics["step_time_s"]

        def close(self):
            pass

    def arm(mesh_obs: bool) -> float:
        cfg = GPTPipeConfig(
            vocab_size=256, block_size=128, dim=128, n_layers=2, n_heads=4,
            n_stages=2, n_microbatches=4, pipeline_parallel=True,
        )
        tcfg = TrainConfig(
            steps=n_steps, batch_size=32, log_every=n_steps, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True, pp_schedule="1f1b",
            mesh_obs=mesh_obs,
            optimizer=OptimizerConfig(max_lr=1e-3, total_steps=n_steps),
        )
        trainer = Trainer(GPTPipe(cfg), tcfg, rules=PP_RULES, mesh=mesh)
        toks = np.random.default_rng(0).integers(0, 256, size=200_000)
        it = lm_batch_iterator(toks, 32, cfg.block_size,
                               sharding=batch_sharding(mesh))
        w = _Last()
        trainer.fit(it, writer=w)
        return float(w.step_time)

    walls = [arm(obs) for obs in (False, True, True, False)]  # A B B A
    off = (walls[0] + walls[3]) / 2
    on = (walls[1] + walls[2]) / 2
    print(json.dumps({
        "mesh_obs_overhead": {
            "steps_per_arm": n_steps,
            "step_time_s_off": round(off, 6),
            "step_time_s_on": round(on, 6),
            "mesh_obs_overhead_pct": round(100 * (on - off) / off, 2),
        }
    }), flush=True)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--mesh-obs", action="store_true",
                   help="run only the paired ABBA mesh-obs overhead arm")
    args = p.parse_args()

    import jax

    if len(jax.devices()) >= 8:
        if args.mesh_obs:
            _mesh_obs_overhead_body()
        else:
            _body(args.stages, args.batch)
        return 0
    # re-exec on the virtual CPU mesh (same recipe as __graft_entry__)
    import re

    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    here = pathlib.Path(__file__).resolve().parent.parent
    if args.mesh_obs:
        snippet = (
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.path.insert(0, {str(here)!r}); "
            "from tools.bench_pipeline import _mesh_obs_overhead_body; "
            "_mesh_obs_overhead_body()"
        )
    else:
        snippet = (
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.path.insert(0, {str(here)!r}); "
            "from tools.bench_pipeline import _body; "
            f"_body({args.stages}, {args.batch})"
        )
    proc = subprocess.run([sys.executable, "-c", snippet], env=env,
                          cwd=str(here))
    return proc.returncode


if __name__ == "__main__":
    raise SystemExit(main())
