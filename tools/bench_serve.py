"""Serving-throughput benchmark -> BENCH_serve.json.

Thin wrapper over `python -m solvingpapers_tpu.cli serve-bench` (one
parser, one call site — the two entry points cannot drift) that defaults
--config to llama3_shakespeare and --out to BENCH_serve.json, keeping the
artifact in the same {metric, value, unit, vs_baseline, detail} shape as
the BENCH_r0*.json scorecards so the serving trajectory stays comparable
across rounds.

Usage: python tools/bench_serve.py [--config llama3_shakespeare]
       [--requests 32] [--slots 8] [--out BENCH_serve.json]
       (any `cli serve-bench` flag passes through)

BENCH_serve.json is JSON-lines, one entry per workload. The default run
overwrites it with the Poisson entry; re-run with
`--shared-prefix --append` to add the prefix-cache workload entry
(cache-on vs cache-off TTFT over K shared system prompts), with
`--sampling --append` for the per-request-sampling workload (mixed
temperature/top-p/top-k/min-p vs all-greedy on the same trace), and
with `--paged --append` for the paged-KV-pool workload (ABBA-paired
paged vs lane throughput, equal-HBM capacity arm, zero-copy
shared-prefix TTFT), with `--http --append` for the HTTP soak
(the Poisson trace as N concurrent SSE clients through the OpenAI
front door, ABBA-paired against direct engine.submit: req/s,
client-side TTFT/p99 ITL, http_overhead_pct, stream_token_exact), and
with `--speculative --append` for the speculative-decoding workload
(spec-on vs spec-off delivered tokens/sec on a briefly-trained model,
greedy token-exactness, acceptance rate, and the temperature-2.0
zero-acceptance adversarial overhead), and with `--kv-quant int8
--append` for the quantized-KV workload (teacher-forced greedy-token
agreement vs the exact pool on a briefly-trained model, ABBA-paired
like-for-like Poisson overhead, and an equal-HBM capacity arm booking
int8+scale slots at the f32 paged pool's resident byte budget).

and with `--slo --append` for the SLO-observatory workload (per-request
SLO classes — interactive/standard/batch — through an slo_targets
engine, ABBA-paired against the plain engine: slo_overhead_pct,
per-class attainment/burn, and goodput_tokens_per_s, the tokens
delivered inside their latency targets),

and with `--chaos --append` for the fault-tolerance soak (one seeded
fault schedule — NaN/Inf slot poisons, synthetic XlaRuntimeError + OOM,
a step stall, a journal_write io_error — through a fault-free
reference, a ladder-off chaos arm and a degradation-ladder arm:
streams_survived, survivor token-exactness, fault_recovery_s, the
zero-leak drain invariant, goodput ladder-on vs ladder-off, the
degraded-journal path, and the ABBA-paired armed-but-quiet
fault_overhead_pct),

and with `--journal --append` for the durability workload (ABBA-paired
journal-on vs journal-off req/s — journal_overhead_pct, fsync batched
per step — plus a kill-and-recover arm: abandon a journaled engine
mid-decode, replay the journal through a fresh one, and record
recovery_wall_s / recovered_requests / recovered_token_exact with the
zero-leak drain invariant),

and with `--replay --append` for the replay-observatory workload
(journal a seeded greedy+stochastic workload on a briefly-trained
model, replay it through serve/replay.py against the identical config
on BOTH pool layouts — replay_byte_exact, the never-flip gate — and
against an int8-kv candidate — replay_agreement_rate, the graded
teacher-forced score held to the same >= 0.99 band as --kv-quant,
with quant_byte_exact_rate / replay_first_divergence_p50 disclosing
how fast byte exactness decays under the lossy candidate),

and with `--fleet --append` for the fleet-serving workload (ABBA-paired
1-replica FleetRouter vs bare engine req/s — router_overhead_pct, the
pure routing tax — plus a drain-migration arm: a journaled 2-replica
fleet mid-decode drain of r0, peers adopting its live streams through
the recover() path, recording migration_wall_s / migrated_streams /
migrated_token_exact / fleet_token_exact with zero-leak on BOTH
replicas).

Every entry records the `kv_dtype` / `kv_pool_bytes` /
`greedy_agreement_rate` triple (exact pools report their compute dtype
and 1.0) so the trajectory stays comparable across quantized rounds,
plus (schema v2) a provenance stamp — git sha, timestamp, jax/jaxlib,
host device — that `tools/bench_check.py` keys its regression gate on.

Add `--trace` to any workload to run one extra flight-recorded arm: the
entry gains `trace_overhead_pct` (tracing-on vs tracing-off req/s on the
same arrival trace — the tracer's < 2% budget), and `--trace-out` gets
the Chrome trace-event JSON for Perfetto / `cli trace-summary`.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    from solvingpapers_tpu.cli import main as cli_main

    argv = list(sys.argv[1:])
    if not any(a == "--config" or a.startswith("--config=") for a in argv):
        # shared-prefix needs prefill compute to dominate dispatch overhead:
        # gpt_shakespeare's 8-layer / 256-position config shows the cache's
        # effect honestly on CPU; llama3_shakespeare (128 positions) stays
        # the Poisson-throughput default for cross-round comparability
        # --paged shares --shared-prefix's reasoning for its prefix
        # sub-arm: the 256-position config's long stems are the regime
        # where the hit-TTFT claim is measured
        # --speculative trains the model briefly before benching (draft
        # quality is the mechanism) — gpt_tiny fits a few hundred steps
        # in seconds
        def flagged(name):
            # value-taking flags also spell --flag=value
            return any(a == name or a.startswith(name + "=") for a in argv)

        if (flagged("--speculative") or flagged("--kv-quant")
                or "--replay" in argv):
            default = "gpt_tiny_long"
        elif "--shared-prefix" in argv or "--paged" in argv:
            default = "gpt_shakespeare"
        else:
            default = "llama3_shakespeare"
        argv += ["--config", default]
    if not any(a == "--out" or a.startswith("--out=") for a in argv):
        argv += ["--out", "BENCH_serve.json"]
    return cli_main(["serve-bench", *argv])


if __name__ == "__main__":
    sys.exit(main())
