"""Quality-parity harness: a pinned, deterministic convergence suite run
every round, with round-over-round regression tracking
(`artifacts/parity/parity.json`).

The reference's recorded quality numbers (BASELINE.md / SURVEY.md §6):
gpt val loss 1.8871 @ 1k steps (gpt-jax.ipynb cell 18), dsv3 loss
2.90068/ppl 18.18644 @ 10k (deepseekv3/readme.md:73), ViT 97.25%, KD
97.50%. TinyStories/MNIST/Shakespeare are not fetchable here (zero
egress), so the suite pins the SAME synthetic corpora every round (char
corpus seed 0; separable image set) — numbers are comparable across
rounds and regressions are flagged, while real-data parity runs remain a
hardware/data question, not a code one: pass --data-path / --image-path
with local copies of the real sets to produce the reference-comparable
numbers with no code change.

Usage: python tools/parity_suite.py [--round N] [--fast]
  --fast trims step counts ~8x (CI smoke); default is the full pinned
  schedule (~10 min on one v5e chip).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

REGRESSION_TOL = {  # metric -> allowed worsening vs the best prior round
    "val_loss": 0.05,
    "accuracy": -0.01,  # may drop at most 1 point
    "gap_to_entropy": 0.05,
    "gap_to_bayes": 0.02,
}

# Absolute quality bar for the entropy-calibrated (markov) rows: held-out
# loss must land within this many nats of the corpus' exact entropy rate.
# A memorizing model sits near ln(64)-H ~= 1.8 nats above the floor, so
# this target separates generalization from table lookup by ~7x margin.
GAP_TARGET_NATS = 0.25

# Absolute bar for the Bayes-calibrated vision rows (vit_bayes/kd_bayes):
# test accuracy must land within this many points of the set's exactly
# computable Bayes-optimal accuracy (data/synthetic.GaussianImageSource).
# A blind classifier sits ~0.77 below the ceiling; the matched filter is
# learnable by every model in the zoo, so 5 points is a generous margin.
GAP_TARGET_ACC = 0.05


def _run_lm(name: str, steps: int, data_path: str | None):
    import jax

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run,
        init_fn_for,
        loss_fn_for,
        rules_for,
    )
    from solvingpapers_tpu.sharding import batch_sharding, create_mesh
    from solvingpapers_tpu.train import Trainer

    cfg = get_config(name, steps=steps)
    if data_path and cfg.data.get("source") != "markov":
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": data_path})
    mesh = create_mesh(cfg.train.mesh)
    cfg, model, _, train_iter, eval_iter_fn = build_char_lm_run(
        cfg, sharding=batch_sharding(mesh)
    )
    trainer = Trainer(model, cfg.train, loss_fn=loss_fn_for(cfg),
                      init_fn=init_fn_for(cfg), mesh=mesh, rules=rules_for(cfg))
    t0 = time.perf_counter()
    state = trainer.fit(train_iter)
    val = trainer.evaluate(state, eval_iter_fn())
    wall = time.perf_counter() - t0
    out = {"steps": steps, "wall_s": round(wall, 1)}
    out.update({k: round(float(v), 5) for k, v in val.items()})
    if cfg.data.get("source") == "markov":
        from solvingpapers_tpu.data.synthetic import markov_entropy_nats

        h = markov_entropy_nats(cfg.data)
        out["entropy_nats"] = round(h, 5)
        out["gap_to_entropy"] = round(out["val_loss"] - h, 5)
    return out


def _run_image(name: str, steps: int, image_path: str | None):
    import jax

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.sharding import create_mesh

    cfg = get_config(name, steps=steps)
    if image_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": image_path})
    mesh = create_mesh(cfg.train.mesh)
    t0 = time.perf_counter()
    if cfg.model_family == "kd":
        from solvingpapers_tpu.configs.factory import build_image_run
        from solvingpapers_tpu.models.kd import MLPClassifier, teacher_config
        from solvingpapers_tpu.train import Trainer, make_kd_loss_fn

        _, train_iter, eval_iter_fn, cls_loss = build_image_run(cfg, mesh=mesh)
        t_cfg = dataclasses.replace(
            cfg.train, steps=max(steps // 2, 1), checkpoint_dir=None, ckpt_every=0
        )
        teacher = MLPClassifier(teacher_config(dtype=cfg.model.dtype))
        t_state = Trainer(teacher, t_cfg, loss_fn=cls_loss, mesh=mesh).fit(
            train_iter
        )
        student = MLPClassifier(cfg.model)
        kd_loss = make_kd_loss_fn(teacher, jax.device_get(t_state.params))
        trainer = Trainer(student, cfg.train, loss_fn=kd_loss, mesh=mesh)
        state = trainer.fit(train_iter)
        val = trainer.evaluate(state, eval_iter_fn())
    else:
        from solvingpapers_tpu.configs.factory import build_image_run
        from solvingpapers_tpu.train import Trainer

        model, train_iter, eval_iter_fn, loss_fn = build_image_run(cfg, mesh=mesh)
        trainer = Trainer(model, cfg.train, loss_fn=loss_fn, mesh=mesh)
        state = trainer.fit(train_iter)
        val = trainer.evaluate(state, eval_iter_fn())
    wall = time.perf_counter() - t0
    out = {"steps": steps, "wall_s": round(wall, 1)}
    out.update({k: round(float(v), 5) for k, v in val.items()})
    if cfg.data.get("source") == "bayes" and "val_accuracy" in out:
        from solvingpapers_tpu.data.synthetic import GaussianImageSource

        ceiling = GaussianImageSource(
            n_classes=cfg.data.get("n_classes", 10),
            side=cfg.data.get("side", 28),
            snr=cfg.data.get("snr", 2.8),
            seed=cfg.train.seed + 7,
        ).bayes_accuracy
        out["bayes_accuracy"] = round(ceiling, 5)
        out["gap_to_bayes"] = round(ceiling - out["val_accuracy"], 5)
    return out


def check_regressions(history: list[dict], current: dict) -> list[str]:
    """Compare the current round's numbers against the best prior round."""
    flags = []
    for wl, res in current["workloads"].items():
        gap = res.get("gap_to_entropy")
        # the absolute target is calibrated for the full pinned schedule;
        # --fast (trimmed steps) rows keep the relative regression gates only
        if gap is not None and not current.get("fast") and gap > GAP_TARGET_NATS:
            flags.append(
                f"{wl}.gap_to_entropy: {gap} nats above the corpus entropy "
                f"floor (absolute target {GAP_TARGET_NATS})"
            )
        bgap = res.get("gap_to_bayes")
        if bgap is not None and not current.get("fast") and bgap > GAP_TARGET_ACC:
            flags.append(
                f"{wl}.gap_to_bayes: {bgap} below the computable Bayes "
                f"ceiling (absolute target {GAP_TARGET_ACC})"
            )
        for metric, tol in (
            ("val_loss", REGRESSION_TOL["val_loss"]),
            ("gap_to_entropy", REGRESSION_TOL["gap_to_entropy"]),
            ("gap_to_bayes", REGRESSION_TOL["gap_to_bayes"]),
        ):
            if metric not in res:
                continue
            prior = [
                h["workloads"][wl][metric]
                for h in history
                if wl in h.get("workloads", {}) and metric in h["workloads"][wl]
                and h["workloads"][wl].get("steps") == res.get("steps")
            ]
            if prior and res[metric] > min(prior) + tol:
                flags.append(
                    f"{wl}.{metric}: {res[metric]} vs best prior {min(prior)}"
                )
        acc = res.get("val_accuracy")
        if acc is not None:
            prior = [
                h["workloads"][wl]["val_accuracy"]
                for h in history
                if wl in h.get("workloads", {})
                and "val_accuracy" in h["workloads"][wl]
                and h["workloads"][wl].get("steps") == res.get("steps")
            ]
            if prior and acc < max(prior) + REGRESSION_TOL["accuracy"]:
                flags.append(f"{wl}.val_accuracy: {acc} vs best prior {max(prior)}")
    return flags


REFERENCE = {  # the reference's recorded numbers these workloads mirror
    "gpt_shakespeare": {"val_loss": 1.8871, "source": "gpt-jax.ipynb cell 18 (real Shakespeare)"},
    "dsv3_tinystories": {"loss": 2.90068, "perplexity": 18.18644,
                         "source": "deepseekv3/readme.md:73 (TinyStories, 10k steps)"},
    "vit_mnist": {"accuracy": 0.9725, "source": "ViT.ipynb cell 15 (MNIST)"},
    "kd_mnist": {"accuracy": 0.9750, "source": "kd run screenshot (MNIST)"},
    "vit_bayes": {"bayes_ceiling": 0.8703,
                  "source": "GaussianImageSource (exact 1-D integral)"},
    "kd_bayes": {"bayes_ceiling": 0.8703,
                 "source": "GaussianImageSource (exact 1-D integral)"},
}


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--round", type=int, default=None)
    p.add_argument("--fast", action="store_true")
    p.add_argument("--data-path", default=None,
                   help="real text corpus (e.g. shakespeare.txt) for the LM rows")
    p.add_argument("--image-path", default=None,
                   help="real MNIST npz for the vision rows")
    p.add_argument("--out-dir", default="artifacts/parity")
    args = p.parse_args()

    div = 8 if args.fast else 1
    # STEP COUNTS ARE PINNED (VERDICT r4 ask 9): the regression gate only
    # compares rows whose `steps` match a prior round's, so changing a
    # row's schedule silently disengages its gate. Tune eval noise (e.g.
    # eval_batches) or data instead; if a schedule truly must change,
    # record one transition round where BOTH step counts run.
    plan = [
        ("gpt_shakespeare", _run_lm, 1000 // div, args.data_path),
        ("dsv3_tinystories", _run_lm, 2000 // div, args.data_path),
        ("vit_mnist", _run_image, 1200 // div, args.image_path),
        ("kd_mnist", _run_image, 1200 // div, args.image_path),
        # Bayes-calibrated vision rows: accuracy has a computable ceiling
        # (0.8703 at snr 2.8) and an absolute gap target — the saturating
        # separable set can't fail for the interesting reason. Full config
        # schedules: the 0.05 target is calibrated there (vit measured
        # 0.839 at 2000 steps = gap 0.031; 1200 steps leaves 0.073)
        ("vit_bayes", _run_image, 2000 // div, None),
        ("kd_bayes", _run_image, 4000 // div, None),
        # entropy-calibrated rows: val_loss - H is an absolute quality bar
        # (H is the markov corpus' exact entropy rate; memorization fails it)
        ("gpt_markov", _run_lm, 3000 // div, None),
        ("llama3_markov", _run_lm, 3000 // div, None),
        ("gemma_markov", _run_lm, 3000 // div, None),
        # 3000 like the peer LMs (the r3 1200-step pin read as
        # schedule-shopping — VERDICT r3 'what's weak')
        ("dsv3_markov", _run_lm, 3000 // div, None),
    ]

    current: dict = {
        "round": args.round,
        "fast": bool(args.fast),
        "time": time.strftime("%Y-%m-%d %H:%M:%S"),
        "data": {"text": args.data_path or "synthetic(seed 0)",
                 "images": args.image_path or "synthetic separable set"},
        "workloads": {},
        "reference": REFERENCE,
    }
    for name, runner, steps, path in plan:
        print(f"[parity] {name} ({steps} steps)...", flush=True)
        current["workloads"][name] = runner(name, steps, path)
        print(f"[parity] {name}: {current['workloads'][name]}", flush=True)

    os.makedirs(args.out_dir, exist_ok=True)
    hist_path = os.path.join(args.out_dir, "parity.json")
    history = []
    if os.path.exists(hist_path):
        with open(hist_path) as f:
            history = json.load(f)

    flags = check_regressions(history, current)
    current["regressions"] = flags
    history.append(current)
    with open(hist_path, "w") as f:
        json.dump(history, f, indent=1)
    print(f"[parity] wrote {hist_path} ({len(history)} rounds recorded)")
    if flags:
        print("[parity] REGRESSIONS:", *flags, sep="\n  ")
        return 1
    print("[parity] no regressions vs prior rounds")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
