"""CI fleet-trace smoke: the trace fabric end to end on a live fleet.

An in-process 2-replica journaled + traced fleet behind the real HTTP
front door; a blocking request is caught LIVE mid-decode and its
replica drained so the stream migrates to the peer. Then every layer
of the fabric is asserted against the running system:

* `GET /v1/requests/<id>` returns the stitched trail — `fleet.migrated`
  true, both hops listed, and the phase walls (accept -> parse -> route
  -> queue_handoff -> queue/prefill/decode -> migrate -> peer_* ->
  sse_drain) PARTITION the client-observed e2e wall within 5%
  (the migration hop included — the invariant the trail exists for);
* `GET /timeseriesz` answers the rolling retrospective for BOTH
  replicas with at least one sampled window each (artifact);
* `FleetRouter.export_chrome_fleet` writes ONE valid Chrome trace:
  `fleet_manifest` declares router + both replicas, each is its own
  Perfetto process, and the migrated request's `fleet_flow` arrow
  spans >= 3 processes (router -> drained replica -> adopter);
* `cli trace-summary --fleet` exits 0 on the stitched file and 2 on a
  truncated copy (the operator-facing error contract).

Writes a JSON scorecard to --out (uploaded as a CI artifact along with
the stitched trace and the time-series dump); exit 1 on any failed
assertion.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
import zlib

import jax
import jax.numpy as jnp


def build_fleet(jdir: str):
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.serve.api import ApiServer
    from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine
    from solvingpapers_tpu.serve.fleet import FleetRouter

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32,
                          n_layers=2, n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engines = [
        ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=48, decode_block=4, bucket=8,
            max_prefills_per_step=2, api_port=0, trace=True,
            # fast cadence so a seconds-long smoke still rolls windows
            timeseries_interval_s=0.05,
            journal_path=os.path.join(jdir, f"r{i}.jsonl")))
        for i in range(2)
    ]
    router = FleetRouter(engines)  # started loops: the real topology
    srv = ApiServer(
        router=router,
        decode=lambda ids: "".join(chr(97 + i % 26) for i in ids),
        model_name="gpt-tiny-fleet",
    )
    return srv, router


def _get_json(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _live_tokens(rep, rid: str, max_new: int):
    e = rep.engine.journal.lookup(rid)
    if (e is None or e.finished or len(e.tokens) >= max_new
            or not rep.engine.journal.is_live(rid)):
        return None
    return len(e.tokens)


def drain_while_live(router, rid, max_new, thread, deadline_s=120.0):
    """Catch `rid` live mid-decode and drain its replica UNDER the held
    step lock (same discipline as tests/test_fleet.py) — the stream is
    deterministically live at the drain. ``(None, None)`` when it
    finished before the drain could land (caller retries)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        owner = router.owner(rid)
        if owner is not None:
            with owner.loop.lock:
                if _live_tokens(owner, rid, max_new) is not None:
                    return owner, router.drain(owner.rid)
            if not thread.is_alive():
                return None, None
        time.sleep(0.001)
    raise SystemExit(f"{rid} never observed live mid-decode")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--trace-out", default="fleet_trace.json")
    ap.add_argument("--timeseries-out", default="fleet_timeseries.json")
    ap.add_argument("--out", default="fleet_trace_smoke.json")
    ap.add_argument("--max-new", type=int, default=40)
    args = ap.parse_args()

    jdir = tempfile.mkdtemp(prefix="fleet_trace_smoke_")
    srv, router = build_fleet(jdir)
    failures: list[str] = []

    def check(ok, msg: str) -> None:
        print(("ok   " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    # warm traffic: jit both paths, roll time-series windows, give the
    # router routing decisions on both replicas
    for i in range(4):
        body = json.dumps({"prompt": [1 + i, 2, 3, 4], "max_tokens": 4,
                           "temperature": 0}).encode()
        req = urllib.request.Request(
            srv.url("/v1/completions"), data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            r.read()

    # ---- mid-decode drain around a live blocking request
    prompt = [2, 7, 1, 8, 2, 8]
    owner = report = None
    rid = ""
    out: dict = {}
    for attempt in range(8):
        rid = f"smoke-mig-{attempt}"
        out = {}

        def client(rid=rid, out=out):
            req = urllib.request.Request(
                srv.url("/v1/completions"),
                data=json.dumps({"prompt": prompt, "temperature": 0,
                                 "max_tokens": args.max_new}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid}, method="POST")
            t0 = time.monotonic()
            with urllib.request.urlopen(req, timeout=300) as r:
                out["replica"] = r.headers.get("X-Replica-Id")
                out["doc"] = json.loads(r.read())
            out["wall_s"] = time.monotonic() - t0

        t = threading.Thread(target=client)
        t.start()
        owner, report = drain_while_live(router, rid, args.max_new, t)
        t.join(timeout=300)
        if owner is not None:
            break
    check(owner is not None, "drain landed while the stream was live")
    if owner is None:
        srv.close()
        return 1
    check(rid in report.targets, "drained stream adopted by a peer")
    peer, _ = report.targets[rid]
    check(out.get("replica") == peer,
          "blocking response came back from the ADOPTER")
    check(out["doc"]["choices"][0]["finish_reason"] == "length",
          "migrated stream ran to its token budget")

    # ---- the trail: GET /v1/requests/<id> partitions the client wall
    trail = _get_json(srv.url(f"/v1/requests/{rid}"))
    fleet = trail.get("fleet") or {}
    check(fleet.get("migrated") is True, "trail marks the migration")
    check(len(fleet.get("hops") or []) >= 2,
          "trail lists both hops (drained replica + adopter)")
    phases = trail.get("phases") or {}
    check("migrate" in phases and "peer_decode" in phases,
          "trail carries migrate + peer_* phases")
    psum = trail["phase_sum_s"]
    e2e = trail["e2e_s"]
    server_err = abs(psum - e2e)
    check(server_err <= max(0.05 * e2e, 1e-3),
          f"phases partition the server e2e wall "
          f"(sum {psum:.4f}s vs {e2e:.4f}s)")
    wall = out["wall_s"]
    # 5% of the client-observed wall, with a small absolute floor for
    # loopback connect/teardown jitter at smoke scale
    client_err = abs(psum - wall)
    check(client_err <= max(0.05 * wall, 0.02),
          f"phases partition the CLIENT-observed e2e wall within 5% "
          f"(sum {psum:.4f}s vs client {wall:.4f}s)")
    router.undrain(owner.rid)

    # ---- the rolling retrospective
    ts = _get_json(srv.url("/timeseriesz"))
    reps = ts.get("replicas") or {}
    check(sorted(reps) == ["r0", "r1"],
          "/timeseriesz answers for both replicas")
    check(all(d.get("n", 0) >= 1 for d in reps.values()),
          "both replicas sampled at least one window")
    with open(args.timeseries_out, "w") as f:
        json.dump(ts, f)

    # ---- the stitched Perfetto export
    router.export_chrome_fleet(args.trace_out)
    with open(args.trace_out) as f:
        doc = json.load(f)  # must be VALID JSON end to end
    events = doc["traceEvents"]
    manifest = next(e for e in events if e.get("name") == "fleet_manifest")
    check(manifest["args"]["sections"] == ["router", "r0", "r1"],
          "fleet_manifest declares router + both replicas")
    pnames = {e["pid"]: e["args"]["name"] for e in events
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    check(sorted(pnames.values()) == ["r0", "r1", "router"],
          "each section is its own Perfetto process")
    fid = zlib.crc32(rid.encode())
    flow_pids = {e["pid"] for e in events
                 if e.get("cat") == "fleet_flow" and e.get("id") == fid}
    check(len(flow_pids) >= 3,
          f"migrated request's flow spans router + both replicas "
          f"({len(flow_pids)} processes)")
    migrates = [e for e in events if e.get("cat") == "fleet"
                and e.get("name") == "migrate"
                and (e.get("args") or {}).get("rid") == rid]
    check(bool(migrates), "router stamped the migrate span for the rid")

    # ---- the operator summary + its error contract
    from solvingpapers_tpu.cli import main as cli_main

    rc = cli_main(["trace-summary", args.trace_out, "--fleet"])
    check(rc == 0, "cli trace-summary --fleet summarizes the export")
    trunc = args.trace_out + ".trunc"
    with open(args.trace_out) as f:
        raw = f.read()
    with open(trunc, "w") as f:
        f.write(raw[: len(raw) // 2])
    rc = cli_main(["trace-summary", trunc, "--fleet"])
    check(rc == 2, "truncated export refused with exit 2")
    os.unlink(trunc)

    srv.close()
    scorecard = {
        "ok": not failures,
        "failures": failures,
        "rid": rid,
        "phases": phases,
        "phase_sum_s": psum,
        "server_e2e_s": e2e,
        "client_e2e_s": wall,
        "client_partition_err_s": round(client_err, 6),
        "flow_processes": len(flow_pids),
        "trace_out": args.trace_out,
        "timeseries_out": args.timeseries_out,
    }
    with open(args.out, "w") as f:
        json.dump(scorecard, f, indent=2)
    print(("fleet-trace smoke OK" if not failures
           else f"fleet-trace smoke FAILED ({len(failures)})"))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
