"""Bench-regression gate over the BENCH_serve.json trajectory.

BENCH_serve.json accumulated hard-won numbers (2.24x spec speedup,
3.75x quant capacity, sub-2% observatory overheads) with nothing that
notices when a future PR regresses them. This tool closes the loop:

* entries are KEYED BY WORKLOAD (``detail.workload``, with the original
  Poisson entry's missing key defaulting to "poisson") and, for
  scale-sensitive metrics, by SCALE (config / request count / slots /
  token budget) — a CI smoke at 8 requests is never compared against
  the committed 32-request measurement on absolute throughput;
* each candidate entry is compared against the MEDIAN of its workload's
  trailing history, per metric, with direction-aware tolerance bands:
    - relative bands for throughput/latency/bytes style metrics
      (higher-better vs lower-better resolved by name),
    - absolute percentage-point bands for ``*_pct`` overheads (a
      relative band around a near-zero overhead is meaningless),
    - absolute bands for rates in [0, 1] (agreement, attainment,
      acceptance, hit rate),
    - booleans (``stream_token_exact``, ``greedy_token_exact``) must
      never flip to False;
* a trajectory summary covering every workload in the history is
  emitted either way — the human-readable view of where the numbers
  have been;
* exit status 2 on any regression (the CI gate), 0 otherwise.

Modes::

    python tools/bench_check.py                      # self-check the
        committed history: each workload's newest entry vs its trailing
        entries (nothing to compare with single-entry workloads — pass)
    python tools/bench_check.py --candidate smoke.json [--candidate ...]
        gate fresh entries (e.g. CI smoke output) against the committed
        history; widen the bands for smoke noise with --rel-tolerance-pct
        / --pct-tolerance / --rate-tolerance

Same shape as tools/parity_suite.py's `check_regressions`: pure
functions over entry dicts, unit-tested in tests/test_bench_check.py.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys

# ---------------------------------------------------------------- schema

# fields that identify a measurement's scale: absolute numbers are only
# comparable when every one of these matches (a missing key matches a
# missing key — the original entries predate some fields)
SCALE_KEYS = ("config", "n_requests", "n_slots", "max_new_tokens",
              "decode_block")

# booleans that must never regress to False
BOOL_FIELDS = ("stream_token_exact", "greedy_token_exact",
               "survivors_token_exact", "zero_leak", "ladder_zero_leak",
               "slots_clean", "recovered_token_exact",
               "journal_degraded_exercised", "migrated_token_exact",
               "fleet_token_exact", "trail_partition_ok",
               "replay_byte_exact")

# name-pattern -> (kind, higher_is_better); first match wins.
# kind: "pct" = absolute percentage-point band — overheads hover near 0
#       and are the one family comparable ACROSS scales (an 8-request
#       smoke's tracing overhead still means something);
#       "pct_scaled" = absolute pp band, gated on matching scale (the
#       decomposition shares: geometry-dependent fractions of a wall);
#       "rate" = absolute band on a [0, 1]-ish value, gated on matching
#       scale (a smoke's agreement/acceptance reflects its own shorter
#       training/scale, not the committed measurement's);
#       "rel"  = relative band, gated on matching scale (absolute
#       throughput/latency/bytes)
_RULES: tuple[tuple[tuple[str, ...], str, bool], ...] = (
    (("_overhead_pct", "overhead_pct"), "pct", False),
    # decomposition shares (gather/dequant/scatter share of the paged
    # decode wall): absolute pp bands but ONLY at matching scale
    # ("pct_scaled") — unlike instrumentation overheads, a share of the
    # decode wall shifts with decode_block/page_size geometry, so a
    # tiny-shape smoke must not gate against the full-scale median.
    # ROADMAP item 1's kernel driving gather_share_pct DOWN is an
    # improvement and never flags; creeping back up at the same scale
    # does. attention_share_pct is the REMAINDER (goes UP as the taxes
    # die), so it is deliberately ungated: gating it would fail the
    # build on exactly the improvement the decomposition exists to
    # deliver.
    (("attention_share_pct",), None, False),
    (("_share_pct",), "pct_scaled", False),
    # quant_byte_exact_rate is the int8 candidate's DISCLOSED byte
    # divergence — expected well below 1.0 and scale-dependent, so it
    # rides the scale-gated rate band like agreement does (the
    # never-flip identical-config story is `replay_byte_exact` above)
    (("agreement_rate", "acceptance_rate", "hit_rate", "attainment",
      "goodput_ratio", "byte_exact_rate"), "rate", True),
    (("requests_per_sec", "tokens_per_sec", "tokens_per_step",
      "speedup", "peak_active_slots", "streams_survived",
      "recovered_requests", "goodput_ladder_ratio", "_gbps"), "rel", True),
    (("ttft", "itl_", "_itl", "e2e_", "compile_time_s",
      "fault_recovery_s", "_wall_us", "_wall_s"), "rel", False),
    (("hbm_bytes", "pool_bytes", "temp_bytes"), "rel", False),
)


def classify(field: str):
    """(kind, higher_is_better) for a gated detail field, or None for
    fields the gate ignores (counts, knobs, paths, nested dicts, and
    rule rows whose kind is None — explicit ungated names that would
    otherwise match a later pattern)."""
    for patterns, kind, higher in _RULES:
        if any(p in field for p in patterns):
            return None if kind is None else (kind, higher)
    return None


def workload_of(entry: dict) -> str:
    det = entry.get("detail") or {}
    return det.get("workload") or "poisson"


def scale_of(entry: dict) -> tuple:
    det = entry.get("detail") or {}
    return tuple(det.get(k) for k in SCALE_KEYS)


def load_entries(path: str) -> list[dict]:
    entries = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(
                    f"{path}:{i + 1} is not valid JSON ({e.msg}) — "
                    "BENCH files are JSON-lines, one entry per line"
                )
    return entries


# ----------------------------------------------------------------- gate


def _gated_fields(entry: dict) -> dict:
    """The comparable numeric fields of one entry: its `detail` scalars
    plus the top-level `value`/`vs_baseline` (namespaced so they can't
    collide with detail keys)."""
    det = entry.get("detail") or {}
    out = {}
    for k, v in det.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[k] = float(v)
    for k in ("value", "vs_baseline"):
        v = entry.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[f"entry.{k}"] = float(v)
    return out


def classify_entry_field(field: str):
    if field in ("entry.value", "entry.vs_baseline"):
        # meaning differs per workload (req/s, speedup, slots ratio...)
        # but "bigger = better" holds for every committed metric;
        # scale-sensitive, so smokes at other scales skip it
        return "rel", True
    return classify(field)


def compare_entry(candidate: dict, history: list[dict], *,
                  rel_tolerance_pct: float = 25.0,
                  pct_tolerance: float = 10.0,
                  rate_tolerance: float = 0.05):
    """Compare one candidate entry against its workload's trailing
    history. Returns (regressions, notes): regressions are human-
    readable failure strings (empty = gate passes), notes record what
    was compared and what was skipped and why."""
    regressions: list[str] = []
    notes: list[str] = []
    wl = workload_of(candidate)
    if not history:
        notes.append(f"[{wl}] no trailing history — nothing to gate")
        return regressions, notes
    scale_match = [h for h in history if scale_of(h) == scale_of(candidate)]
    cand = _gated_fields(candidate)
    cdet = candidate.get("detail") or {}

    for field in BOOL_FIELDS:
        if field not in cdet:
            continue
        ever_true = any((h.get("detail") or {}).get(field) is True
                        for h in history)
        if ever_true and cdet[field] is not True:
            regressions.append(
                f"[{wl}] {field} flipped to {cdet[field]!r} "
                "(was True in history)"
            )

    compared = 0
    for field, value in sorted(cand.items()):
        spec = classify_entry_field(field)
        if spec is None:
            continue
        kind, higher = spec
        pool = history if kind == "pct" else scale_match
        base_vals = [
            _gated_fields(h)[field] for h in pool
            if field in _gated_fields(h)
        ]
        if not base_vals:
            if kind != "pct" and any(
                field in _gated_fields(h) for h in history
            ):
                notes.append(
                    f"[{wl}] {field}: scale differs from history — "
                    "skipped (scale-sensitive metric)"
                )
            continue
        base = statistics.median(base_vals)
        compared += 1
        if kind in ("pct", "pct_scaled"):
            delta = value - base
            bad = delta > pct_tolerance if not higher \
                else -delta > pct_tolerance
            if bad:
                regressions.append(
                    f"[{wl}] {field}: {value:g} vs baseline {base:g} "
                    f"(Δ {delta:+.2f}pp > {pct_tolerance}pp band)"
                )
        elif kind == "rate":
            delta = (base - value) if higher else (value - base)
            if delta > rate_tolerance:
                regressions.append(
                    f"[{wl}] {field}: {value:g} vs baseline {base:g} "
                    f"(worse by {delta:.3f} > {rate_tolerance} band)"
                )
        else:  # rel
            if base == 0:
                continue
            change = (value - base) / abs(base)
            worse = -change if higher else change
            if worse * 100.0 > rel_tolerance_pct:
                regressions.append(
                    f"[{wl}] {field}: {value:g} vs baseline {base:g} "
                    f"({'-' if higher else '+'}{abs(change) * 100:.1f}% "
                    f"> {rel_tolerance_pct}% band)"
                )
    notes.append(f"[{wl}] compared {compared} metrics against "
                 f"{len(history)} trailing entr"
                 f"{'y' if len(history) == 1 else 'ies'}"
                 f" ({len(scale_match)} at matching scale)")
    return regressions, notes


def check_regressions(history_entries: list[dict],
                      candidates: list[dict], **tol) -> list[str]:
    """Gate `candidates` against `history_entries` (grouped by
    workload); returns every regression string found."""
    by_wl: dict[str, list[dict]] = {}
    for e in history_entries:
        by_wl.setdefault(workload_of(e), []).append(e)
    out: list[str] = []
    for cand in candidates:
        regs, _ = compare_entry(cand, by_wl.get(workload_of(cand), []),
                                **tol)
        out.extend(regs)
    return out


# -------------------------------------------------------------- summary


def _headline(entry: dict) -> str:
    prov = entry.get("provenance") or {}
    sha = (prov.get("git_sha") or "")[:9] or "-"
    return (f"{entry.get('value', '-'):>10} {entry.get('unit', ''):<38.38} "
            f"sha {sha:<9}")


def trajectory_summary(history: list[dict],
                       candidates: list[dict] | None = None) -> str:
    """One line per entry, grouped by workload, oldest first — the
    at-a-glance view of where every workload's headline number has
    been, and where a candidate would take it."""
    by_wl: dict[str, list[dict]] = {}
    for e in history:
        by_wl.setdefault(workload_of(e), []).append(e)
    lines = [f"bench trajectory ({len(history)} entries, "
             f"{len(by_wl)} workloads):"]
    for wl in sorted(by_wl):
        lines.append(f"  {wl}:")
        for e in by_wl[wl]:
            lines.append(f"    {_headline(e)}  [{e.get('metric', '-')}]")
        for c in candidates or []:
            if workload_of(c) == wl:
                lines.append(f"    {_headline(c)}  <- candidate")
    for c in candidates or []:
        if workload_of(c) not in by_wl:
            lines.append(f"  {workload_of(c)} (new workload):")
            lines.append(f"    {_headline(c)}  <- candidate")
    return "\n".join(lines)


# ----------------------------------------------------------------- main


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_check",
        description="Regression gate + trajectory summary over "
                    "BENCH_serve.json",
    )
    ap.add_argument("--history", default="BENCH_serve.json",
                    help="JSON-lines bench history (default "
                         "BENCH_serve.json)")
    ap.add_argument("--candidate", action="append", default=[],
                    help="JSON-lines file of fresh entries to gate "
                         "against the history (repeatable); without "
                         "one, self-check each workload's newest "
                         "committed entry against its trailing ones")
    ap.add_argument("--rel-tolerance-pct", type=float, default=25.0,
                    help="relative band for throughput/latency/bytes "
                         "metrics (default 25)")
    ap.add_argument("--pct-tolerance", type=float, default=10.0,
                    help="absolute percentage-point band for *_pct "
                         "overhead metrics (default 10)")
    ap.add_argument("--rate-tolerance", type=float, default=0.05,
                    help="absolute band for [0,1] rates — agreement/"
                         "attainment/acceptance (default 0.05)")
    args = ap.parse_args(argv)

    history = load_entries(args.history)
    if not history:
        print(f"{args.history} is empty — nothing to gate", file=sys.stderr)
        return 2
    tol = dict(rel_tolerance_pct=args.rel_tolerance_pct,
               pct_tolerance=args.pct_tolerance,
               rate_tolerance=args.rate_tolerance)

    by_wl: dict[str, list[dict]] = {}
    for e in history:
        by_wl.setdefault(workload_of(e), []).append(e)

    regressions: list[str] = []
    notes: list[str] = []
    candidates: list[dict] = []
    if args.candidate:
        for path in args.candidate:
            candidates.extend(load_entries(path))
        for cand in candidates:
            regs, nts = compare_entry(
                cand, by_wl.get(workload_of(cand), []), **tol)
            regressions.extend(regs)
            notes.extend(nts)
    else:
        # self-check: newest committed entry per workload vs its tail
        for wl, entries in sorted(by_wl.items()):
            regs, nts = compare_entry(entries[-1], entries[:-1], **tol)
            regressions.extend(regs)
            notes.extend(nts)

    print(trajectory_summary(history, candidates))
    print()
    for n in notes:
        print(f"note: {n}")
    if regressions:
        print()
        for r in regressions:
            print(f"REGRESSION: {r}", file=sys.stderr)
        print(f"\nbench_check: {len(regressions)} regression(s) — "
              "failing the gate", file=sys.stderr)
        return 2
    print("\nbench_check: OK — no regressions against the trailing "
          "history")
    return 0


if __name__ == "__main__":
    sys.exit(main())
