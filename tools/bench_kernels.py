"""Kernel microbenchmarks -> BENCH_kernels.json.

Thin wrapper over `python -m solvingpapers_tpu.cli kernel-bench` (one
parser, one call site — the two entry points cannot drift, the
tools/bench_serve.py pattern) that defaults --out to BENCH_kernels.json.

The harness (serve/kernel_bench.py) times the serving stack's hot inner
ops IN ISOLATION — fenced, min-of-reps, at real serving shapes — over
the full (pool layout x kv_quant) grid:

    gather           pool -> logical lane view (`gather_lanes`, the
                     paged tax's headline op; int8 rows dequantize on
                     read; the lane pool's f32 row is the in-place
                     per-leaf READ the lane program actually does —
                     every byte touched, nothing materialized)
    scatter          one decode token's write-back per slot
    quant_roundtrip  quantize+dequantize of the full lane view
    splice           prefix-cache segment traffic (lane splice/extract
                     copies; paged per-slot page-window ops)
    sample           `fused_sample` on a mixed batch
    spec_verify      the speculative 1+k verify window

BENCH_kernels.json is JSON-lines, one entry per grid cell (4 per run:
{lane, paged} x {f32, int8}), each carrying `bench_provenance` exactly
like BENCH_serve.json and gated by tools/bench_check.py
(`--history BENCH_kernels.json`): the headline `value` is the gather
bandwidth in GB/s (higher-better), the per-family `<family>_wall_us`
detail fields are lower-better at matching scale.

Usage: python tools/bench_kernels.py [--config gpt_shakespeare]
       [--slots 8] [--max-len 256] [--page-size 16] [--reps 5]
       [--out BENCH_kernels.json] (any `cli kernel-bench` flag passes
       through; set JAX_PLATFORMS in the environment)

These numbers are the measured per-component baseline ROADMAP item 1's
fused paged-attention kernel is diffed against — the serve benches join
them with the compile registry's fenced decode wall into the
gather/dequant/scatter/attention `*_share_pct` decomposition on the
paged and kv-quant BENCH_serve.json entries.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> int:
    from solvingpapers_tpu.cli import main as cli_main

    argv = list(sys.argv[1:])
    if not any(a == "--out" or a.startswith("--out=") for a in argv):
        argv += ["--out", "BENCH_kernels.json"]
    return cli_main(["kernel-bench", *argv])


if __name__ == "__main__":
    sys.exit(main())
