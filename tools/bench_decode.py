"""Decode-throughput benchmark: cached scan decode vs reference-style
full-prefix recompute (BENCHMARKS.md).

All four reference LMs generate by re-running the forward on the whole
prefix per token with no cache (SURVEY.md §3.4). Here that costs O(T) full
forwards vs the framework's prefill + lax.scan single-token steps. Both
arms below run jitted on-chip at static shapes — the recompute arm is the
most charitable possible rendition of the reference's pattern (its actual
loops are unjitted python); the gap measured is purely the cache.

Usage: python tools/bench_decode.py [--bs 8] [--prompt 128] [--new 256]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--bs", type=int, default=8)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--new", type=int, default=256)
    p.add_argument("--dim", type=int, default=1024)
    p.add_argument("--layers", type=int, default=24)
    p.add_argument("--model", choices=("llama3", "dsv3"), default="llama3",
                   help="dsv3 = flash-MLA long-context decode (16k prompts "
                        "prefill through the Pallas kernel end-aligned mode)")
    p.add_argument("--prefill-chunk", type=int, default=None)
    p.add_argument("--skip-recompute", action="store_true",
                   help="only measure the cached arm")
    args = p.parse_args()

    from solvingpapers_tpu import ops
    from solvingpapers_tpu.infer import generate

    total = args.prompt + args.new
    extra_variables = None
    if args.model == "dsv3":
        from solvingpapers_tpu.models.deepseekv3 import (
            DeepSeekV3, DeepSeekV3Config,
        )

        # --dim/--layers apply to the dsv3 arm too (heads scale with dim)
        cfg = DeepSeekV3Config(
            vocab_size=32000, block_size=total, dtype="bfloat16",
            dim=args.dim if args.dim != 1024 else 512,
            n_layers=args.layers if args.layers != 24 else 6,
            n_heads=max((args.dim if args.dim != 1024 else 512) // 64, 1),
            use_flash=True, pe_scale=0.02, rope_dim=64,
            dropout=0.0, attn_dropout=0.0,
        )
        model = DeepSeekV3(cfg)
    else:
        from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

        cfg = LlamaConfig(
            vocab_size=32000, dim=args.dim, n_layers=args.layers,
            n_heads=args.dim // 64, n_kv_heads=args.dim // 128,
            max_seq_len=total, dropout=0.0, dtype="bfloat16",
        )
        model = Llama(cfg)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size,
                                          (args.bs, args.prompt)),
        jnp.int32,
    )
    variables = model.init({"params": jax.random.key(0)}, prompt)
    params = variables["params"]
    if args.model == "dsv3":
        extra_variables = {"moe_state": variables["moe_state"]}
    rng = jax.random.key(1)

    def timed(fn, *a, reps=3):
        # fence on a device-side scalar: block_until_ready is not a real
        # fence on axon, and device_get of a full logits tensor would drag
        # tens of MB through the tunnel per rep (observed as minutes-long
        # "hangs" — slice BEFORE transferring)
        fence = lambda out: float(jnp.sum(out[..., -1]))  # noqa: E731
        out = fn(*a)            # compile
        fence(out)
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            out = fn(*a)
            fence(out)
            best = min(best, time.time() - t0)
        return best, out

    # arm 1: cached decode (prefill + scan); generate is already one jitted
    # XLA program — wrapping it in another jit stalls the axon remote
    # compiler indefinitely (observed >25 min vs 27 s unwrapped)
    cached = lambda p_, r: generate(  # noqa: E731
        model, params, p_, r, max_new_tokens=args.new,
        sampler=ops.sample_greedy, extra_variables=extra_variables,
        prefill_chunk=args.prefill_chunk,
    )
    t_cached, out = timed(cached, prompt, rng)

    # prefill-only arm (max_new_tokens=1): isolates the end-aligned
    # flash/causal prefill from the scan decode
    prefill_only = lambda p_, r: generate(  # noqa: E731
        model, params, p_, r, max_new_tokens=1,
        sampler=ops.sample_greedy, extra_variables=extra_variables,
        prefill_chunk=args.prefill_chunk,
    )
    t_prefill, _ = timed(prefill_only, prompt, rng)

    # arm 2: reference-style — a full forward over the final-length prefix
    # per new token. Measured as one jitted full-length forward x `new`
    # (a scan of full forwards stalls the axon remote compiler; this is
    # the charitable rendition anyway: the reference's actual loops are
    # unjitted python with no batching of compile costs)
    t_full = None
    if not args.skip_recompute:
        toks_full = jnp.pad(prompt, ((0, 0), (0, args.new)))
        fwd = jax.jit(lambda t: model.apply({"params": params}, t,
                                            deterministic=True)[0])
        t_one, _ = timed(fwd, toks_full)
        t_full = t_one * args.new

    new_toks = args.bs * args.new
    name = (
        f"dsv3-flash-mla-d{cfg.dim}-L{cfg.n_layers}" if args.model == "dsv3"
        else f"llama3-d{args.dim}-L{args.layers}"
    )
    decode_s = max(t_cached - t_prefill, 1e-9)
    decoded = max(args.new - 1, 1)  # prefill emits token 0; --new 1 is
    out = {                         # effectively a prefill-only run
        "model": name, "bs": args.bs,
        "prompt": args.prompt, "new": args.new,
        "prefill_s": round(t_prefill, 3),
        "prefill_tokens_per_sec": round(args.bs * args.prompt / t_prefill),
        "cached_tokens_per_sec": round(args.bs * decoded / decode_s),
        "cached_ms_per_token": round(decode_s / decoded * 1e3, 3),
    }
    if t_full is not None:
        out["recompute_tokens_per_sec"] = round(new_toks / t_full)
        out["speedup"] = round(t_full / t_cached, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
