"""CI crash-recovery smoke: SIGKILL a journaled `cli serve` mid-decode,
restart it on the same journal, and assert the recovered completions
are byte-identical to an uninterrupted reference.

The in-process kill-and-recover arm (`serve-bench --journal`) abandons
an engine object; this smoke does the real thing — a subprocess
`python -m solvingpapers_tpu.cli serve --journal ...` killed with
SIGKILL while SSE streams are mid-flight — and drives the full client
resume protocol: each stream tracks the last ``id: <rid>:<offset>``
field it saw, reconnects to the RESTARTED server with
``Last-Event-ID``, and the replayed tail must splice byte-identically
onto what was delivered before the kill (greedy streams; same seed and
config on both boots, so the reference run is deterministic).

Also asserts: `/statusz` on the restarted server carries the journal
section with ``recovered_requests`` > 0, and `GET /v1/requests/<id>`
answers from the journal (``source: "journal"``) for streams the
restarted process never saw over HTTP.

Writes a JSON scorecard to --out (uploaded as a CI artifact along with
the journal file itself); exit 1 on any failed assertion.
"""

from __future__ import annotations

import argparse
import json
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request


def wait_healthy(port: int, proc, timeout_s: float = 420.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SystemExit(
                f"server exited early with rc {proc.returncode}"
            )
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=2
            ) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    raise SystemExit("server never became healthy")


def start_server(port: int, journal: str, extra=()) -> subprocess.Popen:
    cmd = [
        sys.executable, "-m", "solvingpapers_tpu.cli", "serve",
        "--config", ARGS.config, "--port", str(port),
        "--journal", journal, "--slots", "2", "--decode-block", "4",
        "--max-len", "192", "--seed", "0", *extra,
    ]
    proc = subprocess.Popen(cmd)
    wait_healthy(port, proc)
    return proc


class SseClient(threading.Thread):
    """One SSE completion stream: collects text and the last event id;
    a dropped connection (the SIGKILL) is recorded, not raised."""

    def __init__(self, port: int, rid: str, prompt, max_tokens: int,
                 resume_from: str | None = None):
        super().__init__(daemon=True)
        self.port = port
        self.rid = rid
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.resume_from = resume_from
        self.text = ""
        self.last_id: str | None = None
        self.finish_reason: str | None = None
        self.done = False
        self.dropped = False

    def run(self) -> None:
        headers = {"Content-Type": "application/json"}
        if self.resume_from is not None:
            headers["Last-Event-ID"] = self.resume_from
            body = b"{}"
        else:
            headers["X-Request-Id"] = self.rid
            body = json.dumps({
                "prompt": self.prompt, "max_tokens": self.max_tokens,
                "stream": True, "temperature": 0,
            }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.port}/v1/completions",
            data=body, headers=headers, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=600) as r:
                cur = None
                for raw in r:
                    line = raw.decode().rstrip("\n")
                    if line.startswith("id: "):
                        cur = line[4:]
                    elif line.startswith("data: "):
                        payload = line[6:]
                        if payload == "[DONE]":
                            self.done = True
                            return
                        ev = json.loads(payload)
                        choice = (ev.get("choices") or [{}])[0]
                        self.text += choice.get("text", "")
                        if choice.get("finish_reason"):
                            self.finish_reason = choice["finish_reason"]
                        self.last_id = cur
        except (urllib.error.URLError, ConnectionError, OSError):
            self.dropped = True


def run_streams(port: int, rids, prompts, max_tokens: int,
                resume_ids=None) -> list[SseClient]:
    clients = [
        SseClient(port, rid, prompt, max_tokens,
                  resume_from=None if resume_ids is None
                  else resume_ids[i])
        for i, (rid, prompt) in enumerate(zip(rids, prompts))
    ]
    for c in clients:
        c.start()
    return clients


def main() -> int:
    failures: list[str] = []

    def check(ok: bool, msg: str) -> None:
        print(("ok  " if ok else "FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    prompts = [[1 + i, 2, 3, 4, 5, 6, 7, 8] for i in range(ARGS.requests)]
    rids = [f"crash-{i}" for i in range(ARGS.requests)]

    # ---- reference: uninterrupted run, same config/seed
    ref_journal = ARGS.journal + ".ref"
    proc = start_server(ARGS.port, ref_journal)
    try:
        ref = run_streams(ARGS.port, rids, prompts, ARGS.max_new)
        for c in ref:
            c.join(timeout=600)
        check(all(c.done for c in ref), "reference streams completed")
        ref_text = [c.text for c in ref]
    finally:
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)

    # ---- crash run: SIGKILL once every stream has committed tokens
    proc = start_server(ARGS.port, ARGS.journal)
    clients = run_streams(ARGS.port, rids, prompts, ARGS.max_new)
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        offs = [int(c.last_id.rsplit(":", 1)[1]) if c.last_id else 0
                for c in clients]
        if all(4 <= o < ARGS.max_new for o in offs):
            break
        if any(c.done for c in clients):
            break  # model too fast — kill now, some streams finished
        time.sleep(0.02)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    for c in clients:
        c.join(timeout=60)
    killed_mid = [c for c in clients if not c.done]
    check(len(killed_mid) > 0, "SIGKILL landed mid-stream for >= 1 stream")
    print(f"    killed with per-stream offsets "
          f"{[c.last_id for c in clients]}")

    # ---- restart on the same journal: recovery + client resume
    proc = start_server(ARGS.port, ARGS.journal)
    try:
        resumed = []
        for c in clients:
            if c.done:
                continue
            off = c.last_id or f"{c.rid}:0"
            r = SseClient(ARGS.port, c.rid, None, ARGS.max_new,
                          resume_from=off)
            r.pre_text = c.text
            resumed.append(r)
            r.start()
        for r in resumed:
            r.join(timeout=600)
        check(all(r.done for r in resumed),
              "resumed streams ran to [DONE]")
        exact = True
        for r in resumed:
            i = rids.index(r.rid)
            if r.pre_text + r.text != ref_text[i]:
                exact = False
                print(f"    {r.rid}: pre={r.pre_text!r} "
                      f"tail={r.text!r} want={ref_text[i]!r}")
        for c in clients:
            if c.done and c.text != ref_text[rids.index(c.rid)]:
                exact = False
        check(exact, "recovered completions byte-identical to the "
                     "uninterrupted reference")

        with urllib.request.urlopen(
            f"http://127.0.0.1:{ARGS.port}/statusz", timeout=10
        ) as r:
            statusz = json.loads(r.read())
        check("journal" in statusz, "/statusz carries the journal section")
        jsec = statusz.get("journal", {})
        check(jsec.get("recovered_requests", 0) >= len(resumed),
              f"statusz recovered_requests >= {len(resumed)}")
        check(jsec.get("degraded") is False, "journal not degraded")

        # journal fallback: the restarted process never saw these over
        # HTTP as ordinary registry entries
        probe = resumed[0].rid if resumed else rids[0]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{ARGS.port}/v1/requests/{probe}",
            timeout=10,
        ) as r:
            doc = json.loads(r.read())
        check(doc.get("source") == "journal",
              "GET /v1/requests/<id> answered from the journal")
        check(doc.get("state") == "finished"
              and len(doc.get("tokens", [])) == ARGS.max_new,
              "journal doc carries the full completion")
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()

    out = {
        "requests": ARGS.requests,
        "streams_killed_mid_decode": len(killed_mid),
        "streams_resumed": len(resumed),
        "recovered_token_exact": not failures
        or all("byte-identical" not in f for f in failures),
        "statusz_journal": jsec,
        "failures": failures,
    }
    with open(ARGS.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[smoke] wrote {ARGS.out}")
    return 1 if failures else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--config", default="gpt_shakespeare")
    ap.add_argument("--port", type=int, default=8611)
    ap.add_argument("--journal", default="crash_smoke.jsonl")
    ap.add_argument("--out", default="crash_smoke.json")
    ap.add_argument("--requests", type=int, default=3)
    ap.add_argument("--max-new", type=int, default=48)
    ARGS = ap.parse_args()
    sys.exit(main())
