"""Reproducible 350M llama3 single-chip scaling study (BENCHMARKS.md).

Measures steady-state training step time / tokens-per-sec / MFU for the
342M-param llama3 config (dim 1024, 24 layers, 16 q / 8 kv heads, seq 1024,
vocab 32000, bf16) on the attached TPU. Timing is honest: each timed segment
ends with a device_get of a value that depends on the computation (the axon
platform's block_until_ready is not a real fence — see
.claude/skills/verify/SKILL.md).

Usage: python tools/scale_350m.py [--bs 8] [--flash 1] [--remat 0]
       [--block-q N] [--block-k N] [--steps 20] [--seq 1024]
       [--profile-dir DIR]
--block-q/--block-k default to the kernel's DEFAULT_BLOCK (512; pass 128
to reproduce the pre-sweep rows in BENCHMARKS.md). Timing mirrors bench.py:
long warmup to fill the dispatch queue, then best of 3 windows (the
tunnelled device has bursty transport noise), each fenced by a device_get.
"""

from __future__ import annotations

import argparse
import functools
import json
import math
import time

import jax
import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--bs", type=int, default=8)
    p.add_argument("--seq", type=int, default=1024)
    p.add_argument("--flash", type=int, default=1)
    p.add_argument("--remat", type=int, default=0)
    p.add_argument("--block-q", type=int, default=None,
                   help="override kernel DEFAULT_BLOCK")
    p.add_argument("--block-k", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args()

    from solvingpapers_tpu import kernels
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.metrics.mfu import (
        chip_peak_flops,
        transformer_flops_per_token,
    )
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    import importlib

    # kernels/__init__ re-exports a function named flash_attention that
    # shadows the submodule on attribute access; go through importlib
    _fa_mod = importlib.import_module(
        "solvingpapers_tpu.kernels.flash_attention"
    )
    _sf_mod = importlib.import_module("solvingpapers_tpu.kernels.sharded_flash")

    block_q = args.block_q or _fa_mod.DEFAULT_BLOCK
    block_k = args.block_k or _fa_mod.DEFAULT_BLOCK
    if (block_q, block_k) != (_fa_mod.DEFAULT_BLOCK, _fa_mod.DEFAULT_BLOCK):
        # experiment knob: route every flash call site through custom block
        # sizes. models/layers.py re-imports kernels.flash_attention per
        # call; sharded_flash bound the name at import, so patch both.
        patched = functools.partial(
            _fa_mod.flash_attention, block_q=block_q, block_k=block_k
        )
        kernels.flash_attention = patched
        _sf_mod.flash_attention = patched

    cfg = LlamaConfig(
        vocab_size=32000, dim=1024, n_layers=24, n_heads=16, n_kv_heads=8,
        max_seq_len=args.seq, dropout=args.dropout, dtype="bfloat16",
        use_flash=bool(args.flash), remat=bool(args.remat),
    )
    tcfg = TrainConfig(
        steps=args.steps, batch_size=args.bs, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-4, total_steps=1000),
    )
    trainer = Trainer(Llama(cfg), tcfg)
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, size=2_000_000)
    it = lm_batch_iterator(toks, args.bs, args.seq, seed=0)
    batch = next(it)
    state = trainer.init_state(batch)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    trainer._build_steps()

    # compile + warmup long enough to fill the dispatch queue (bench.py's
    # methodology), fenced by a value fetch
    for _ in range(10):
        state, m = trainer._train_step(state, next(it))
    _ = float(jax.device_get(m["train_loss"]))

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    best = float("inf")
    for _ in range(3):  # best of 3 windows: tunnel transport is bursty
        t0 = time.time()
        for _ in range(args.steps):
            state, m = trainer._train_step(state, next(it))
        _ = float(jax.device_get(m["train_loss"]))
        best = min(best, time.time() - t0)
    dt = best / args.steps
    if args.profile_dir:
        jax.profiler.stop_trace()

    tok_s = args.bs * args.seq / dt
    fpt = transformer_flops_per_token(n_params, cfg.n_layers, cfg.dim, args.seq)
    peak = chip_peak_flops()
    print(json.dumps({
        "params_m": round(n_params / 1e6, 1), "bs": args.bs, "seq": args.seq,
        "flash": bool(args.flash), "remat": bool(args.remat),
        "block_q": block_q, "block_k": block_k,
        "step_ms": round(dt * 1e3, 1), "tokens_per_sec": round(tok_s),
        # unknown chips have no peak entry (NaN sentinel): omit the key —
        # json.dumps would emit a bare non-RFC-8259 NaN token
        **({"mfu": round(tok_s * fpt / peak, 4)}
           if math.isfinite(peak) else {}),
    }))


if __name__ == "__main__":
    main()
